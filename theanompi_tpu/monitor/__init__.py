"""theanompi_tpu.monitor — unified telemetry for rules, service,
launchers, and bench.

One process-wide monitor with four faces (docs/OBSERVABILITY.md is the
operator's reference):

* **metrics registry** (``registry.py``) — counters / gauges /
  streaming histograms with labels, snapshot to JSONL + Prometheus
  text;
* **span tracing** (``spans.py``) — nested wall-clock spans that fence
  on device arrays and emit ``jax.profiler.TraceAnnotation`` markers;
* **health** (``health.py``) — heartbeat file + stall watchdog +
  straggler detection;
* **postmortem** (``postmortem.py``) — crash dump of the registry,
  open spans, and recent step timings.

Enablement contract (the part every call site relies on): monitoring
is OFF unless a run dir is configured — either ``monitor.session(
run_dir=...)`` from a rule/launcher, or the ``THEANOMPI_TPU_MONITOR``
env var pointing at a directory.  When off, every facade function
returns after ONE boolean check and the registry receives **zero
writes** (tested: ``tests/test_monitor.py::test_disabled_is_noop``);
instrumented hot loops pay one branch per call.

Typical wiring (this is what rules/bsp.py does):

    from theanompi_tpu import monitor

    with monitor.session(run_dir=args.monitor_dir, rank=host):
        with monitor.span("epoch", epoch=str(e)):
            t0 = time.monotonic()
            model.train_iter(it, recorder)
            monitor.observe_step(time.monotonic() - t0,
                                 phase="train", step=it)

Files written under the run dir (rank-suffixed so multi-host runs on a
shared filesystem never collide):

    metrics_rank{r}.jsonl    latest registry snapshot, 1 series/line
    metrics_rank{r}.prom     Prometheus text dump (final flush)
    heartbeat_rank{r}.json   liveness + phase + progress age
    postmortem_rank{r}.json  on unhandled rule-loop exceptions
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from typing import Any, Iterator

from theanompi_tpu.monitor.health import HeartbeatReporter, StragglerDetector
from theanompi_tpu.monitor.postmortem import (
    build_postmortem,
    dump_postmortem as _dump_postmortem_file,
)
from theanompi_tpu.monitor.registry import (
    MetricsRegistry,
    tree_bytes,
    tree_dtypes,
)
from theanompi_tpu.monitor.spans import NULL_SPAN, Span, open_spans
from theanompi_tpu.monitor import trace

ENV_VAR = "THEANOMPI_TPU_MONITOR"

#: how many recent step durations the postmortem report carries
RECENT_STEPS = 64

__all__ = [
    "ENV_VAR", "MetricsRegistry", "Span", "StragglerDetector",
    "HeartbeatReporter", "enabled", "monitor_dir", "registry", "session",
    "inc", "set_gauge", "add_gauge", "observe", "span", "progress",
    "observe_step", "flush", "dump_postmortem", "open_spans",
    "tree_bytes", "tree_dtypes", "reset_for_tests", "snapshot_path",
    "trace",
]


class _State:
    """All mutable module state in one bag, swap-able for tests."""

    def __init__(self):
        self.registry = MetricsRegistry()
        self.enabled = False
        self.run_dir: str | None = None
        self.rank = 0
        #: file-name discriminator: ``rank{r}`` for training ranks, a
        #: caller-chosen name for co-located non-rank processes (a
        #: tmserver beside a trainer must not clobber rank0's files)
        self.suffix = "rank0"
        self.heartbeat: HeartbeatReporter | None = None
        self.straggler: StragglerDetector | None = None
        self.exporter = None  # monitor/export.py Exporter when tracing
        self.recent_steps: deque[float] = deque(maxlen=RECENT_STEPS)
        self.depth = 0


_state = _State()
_lock = threading.RLock()


# ---------------------------------------------------------------------------
# Introspection
# ---------------------------------------------------------------------------


def enabled() -> bool:
    return _state.enabled


def monitor_dir() -> str | None:
    return _state.run_dir


def registry() -> MetricsRegistry:
    """The process registry.  Always exists (so its ``write_count``
    can prove the disabled no-op path); only the facade writes to it
    when enabled."""
    return _state.registry


def snapshot_path() -> str | None:
    if _state.run_dir is None:
        return None
    return os.path.join(_state.run_dir,
                        f"metrics_{_state.suffix}.jsonl")


# ---------------------------------------------------------------------------
# Session lifecycle
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def session(run_dir: str | None = None, rank: int = 0,
            interval: float | None = None,
            stall_after: float | None = None,
            name: str | None = None) -> Iterator[bool]:
    """Activate monitoring for the enclosed block; yields whether it
    is live.  ``run_dir=None`` falls back to ``$THEANOMPI_TPU_MONITOR``;
    with neither set the block runs with monitoring fully disabled (the
    strict no-op path).  Reentrant: nested sessions share the outer
    one's registry/heartbeat and only the outermost exit flushes and
    tears down.  An exception escaping the block triggers the
    postmortem dump before re-raising."""
    resolved = run_dir or os.environ.get(ENV_VAR) or None
    if not resolved:
        yield False
        return
    with _lock:
        # activate BEFORE counting the depth: if activation raises
        # (bad interval env value, unwritable dir) the count must not
        # leak, or every later session would believe an outer one is
        # live and silently record nothing
        if _state.depth == 0:
            _activate(resolved, rank, interval, stall_after, name)
        _state.depth += 1
    try:
        yield True
    except BaseException as e:
        dump_postmortem(e)
        raise
    finally:
        with _lock:
            _state.depth -= 1
            if _state.depth == 0:
                _finalize()


def _activate(run_dir: str, rank: int, interval: float | None,
              stall_after: float | None,
              name: str | None = None) -> None:
    os.makedirs(run_dir, exist_ok=True)
    # fresh registry per session: consecutive sessions in one process
    # (a sweep, a notebook) must not merge each other's series into
    # their snapshot files
    _state.registry = MetricsRegistry()
    _state.recent_steps.clear()
    _state.run_dir = run_dir
    _state.rank = rank
    _state.suffix = name or f"rank{rank}"
    _state.straggler = StragglerDetector(registry=_state.registry)
    if interval is None:
        interval = float(os.environ.get(
            "THEANOMPI_TPU_MONITOR_INTERVAL", "5"))
    if stall_after is None:
        stall_after = float(os.environ.get(
            "THEANOMPI_TPU_MONITOR_STALL_S", "60"))
    _state.heartbeat = HeartbeatReporter(
        run_dir, rank=rank, registry=_state.registry,
        interval=interval, stall_after=stall_after,
        snapshot_path=os.path.join(run_dir,
                                   f"metrics_{_state.suffix}.jsonl"),
        suffix=_state.suffix,
    ).start()
    _state.registry.set_gauge("monitor/enabled", 1.0)
    # tracing/export ride the session lifecycle: re-read the env
    # switches here (so launcher-exported vars take effect) and start
    # the exporter only when tracing or a collector is configured —
    # otherwise nothing below allocates and the strict no-op contract
    # of the disabled path is untouched
    trace.activate_from_env()
    from theanompi_tpu.monitor import export as _export

    _state.exporter = _export.maybe_start(
        run_dir, _state.suffix, rank, _state.registry)
    _state.enabled = True


def _finalize() -> None:
    _state.enabled = False
    # the final snapshot must say the session ENDED, and a later
    # session's postmortem must not inherit this one's step timings
    _state.registry.set_gauge("monitor/enabled", 0.0)
    _state.recent_steps.clear()
    hb, _state.heartbeat = _state.heartbeat, None
    if hb is not None:
        hb.stop()
    ex, _state.exporter = _state.exporter, None
    if ex is not None:
        from theanompi_tpu.monitor import export as _export

        _export.set_exporter(None)
        ex.stop()
    run_dir, suffix = _state.run_dir, _state.suffix
    if run_dir is not None:
        try:
            _state.registry.write_jsonl(
                os.path.join(run_dir, f"metrics_{suffix}.jsonl"))
            with open(os.path.join(run_dir,
                                   f"metrics_{suffix}.prom"), "w") as f:
                f.write(_state.registry.to_prometheus())
        except OSError:
            pass
    _state.run_dir = None
    _state.straggler = None


def reset_for_tests() -> None:
    """Hard reset: stop any heartbeat thread and swap in a fresh
    state/registry.  Test fixture use only."""
    global _state
    with _lock:
        hb = _state.heartbeat
        if hb is not None:
            hb.stop()
        ex = _state.exporter
        if ex is not None:
            from theanompi_tpu.monitor import export as _export

            _export.set_exporter(None)
            ex.stop()
        trace.reset_for_tests()
        _state = _State()


# ---------------------------------------------------------------------------
# Hot-path instrumentation (all strictly gated)
# ---------------------------------------------------------------------------


def inc(name: str, amount: float = 1.0, /, **labels) -> None:
    if not _state.enabled:
        return
    _state.registry.inc(name, amount, **labels)


def set_gauge(name: str, value: float, /, **labels) -> None:
    if not _state.enabled:
        return
    _state.registry.set_gauge(name, value, **labels)


def add_gauge(name: str, delta: float, /, **labels) -> None:
    if not _state.enabled:
        return
    _state.registry.add_gauge(name, delta, **labels)


def observe(name: str, value: float, /, **labels) -> None:
    if not _state.enabled:
        return
    _state.registry.observe(name, value, **labels)


def span(name: str, /, fence: Any = None, **labels):
    """A context manager timing the block into ``span_ms{name=...}``;
    the shared no-op when monitoring is disabled.  ``fence=`` blocks on
    a device array/pytree at exit so device time is charged to this
    span (see spans.py)."""
    if not _state.enabled:
        return NULL_SPAN
    return Span(name, registry=_state.registry, fence=fence, **labels)


def progress(phase: str | None = None, step: int | None = None,
             worker: int | None = None) -> None:
    """Feed the heartbeat/watchdog: call whenever work advances."""
    if not _state.enabled:
        return
    hb = _state.heartbeat
    if hb is not None:
        hb.progress(phase, step, worker)


def observe_step(seconds: float, phase: str | None = None,
                 step: int | None = None,
                 worker: int | None = None) -> bool:
    """One training-step observation: feeds the ``step_ms`` histogram,
    the heartbeat, the postmortem's recent-step ring, and (when
    ``worker`` is given — async rules) the straggler detector.
    Returns True while the worker is flagged as a straggler."""
    if not _state.enabled:
        return False
    _state.registry.observe(
        "step_ms", seconds * 1e3,
        worker=str(worker) if worker is not None else "0")
    _state.recent_steps.append(seconds)
    hb = _state.heartbeat
    if hb is not None:
        hb.progress(phase, step, worker)
    if worker is not None and _state.straggler is not None:
        return _state.straggler.observe(worker, seconds)
    return False


def flush() -> str | None:
    """Write the snapshot JSONL now (also happens periodically from
    the heartbeat thread and at session exit)."""
    if not _state.enabled or _state.run_dir is None:
        return None
    path = snapshot_path()
    try:
        _state.registry.write_jsonl(path)
    except OSError:
        return None
    return path


def dump_postmortem(exc: BaseException | None = None) -> str | None:
    """Write the crash report to the run dir; no-op when disabled.
    Called automatically when an exception escapes ``session()``."""
    if not _state.enabled or _state.run_dir is None:
        return None
    return _dump_postmortem_file(
        _state.run_dir, _state.rank, exc,
        registry=_state.registry,
        recent_steps=list(_state.recent_steps),
        suffix=_state.suffix)
