"""Crash postmortem — dump everything the monitor knows at the point
of death.

When a rule loop dies on an unhandled exception, the useful questions
are always the same: *what phase was each thread in, what did the
metrics look like, and how were the last few steps trending?*  The
postmortem answers all three in one JSON file in the run dir:

    postmortem_rank{rank}.json
      { "ts": ..., "rank": ..., "exception": {type, message,
        traceback}, "open_spans": [...], "recent_steps": [...],
        "metrics": [<registry snapshot>] }

The dump path must never make a crash worse: every section is built
best-effort, and I/O failures are swallowed (the original exception is
the one that matters).
"""

from __future__ import annotations

import json
import os
import time
import traceback
from typing import Iterable

from theanompi_tpu.monitor.registry import MetricsRegistry, atomic_write_text
from theanompi_tpu.monitor.spans import open_spans


def build_postmortem(rank: int, exc: BaseException | None,
                     registry: MetricsRegistry | None = None,
                     recent_steps: Iterable[float] | None = None) -> dict:
    """The postmortem payload as a dict (separated from the writer so
    tests can assert on content without a filesystem)."""
    report: dict = {"ts": time.time(), "rank": rank, "pid": os.getpid()}
    if exc is not None:
        report["exception"] = {
            "type": type(exc).__name__,
            "message": str(exc),
            "traceback": "".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__))[-8000:],
        }
    try:
        report["open_spans"] = open_spans()
    except Exception:
        report["open_spans"] = []
    if recent_steps is not None:
        report["recent_step_ms"] = [round(s * 1e3, 3)
                                    for s in recent_steps]
    if registry is not None:
        try:
            report["metrics"] = registry.snapshot()
        except Exception:
            report["metrics"] = []
    return report


def dump_postmortem(run_dir: str, rank: int, exc: BaseException | None,
                    registry: MetricsRegistry | None = None,
                    recent_steps: Iterable[float] | None = None,
                    suffix: str | None = None) -> str | None:
    """Write ``postmortem_{suffix}.json`` (suffix defaults to
    ``rank{rank}``); returns the path, or None if the write failed
    (never raises — the crash in flight owns the stack)."""
    report = build_postmortem(rank, exc, registry, recent_steps)
    path = os.path.join(run_dir,
                        f"postmortem_{suffix or f'rank{rank}'}.json")
    try:
        atomic_write_text(path, json.dumps(report, indent=1))
    except Exception:
        return None
    return path
