"""Health reporting — heartbeat files, a stall watchdog, straggler
detection.

The r04 bench spent 240 s wedged in device init with zero structured
signal about where; its only output was silence.  The heartbeat closes
that class of blind spot: a reporter thread writes a small per-rank
JSON file every few seconds carrying (phase, step, seconds since last
progress), so any outside observer — an operator, the preflight gate,
a cluster babysitter — can distinguish "slow" from "stuck" without
attaching a debugger.  The same thread runs the watchdog: when no
progress has been reported for ``stall_after`` seconds it names the
stuck phase on stderr (once per stall episode, not every tick) and
counts it in the registry.

``StragglerDetector`` is the multi-worker counterpart: the async rules
feed it per-worker step durations; a worker whose recent median step
time exceeds ``factor`` x the cross-worker rolling median is flagged.
Flags are edge-triggered (counted and logged on transition, cleared on
recovery) so a persistently slow worker doesn't spam.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time
from collections import deque

from theanompi_tpu.monitor.registry import MetricsRegistry, atomic_write_text


class HeartbeatReporter:
    """Background thread: heartbeat file + stall watchdog + periodic
    metrics-snapshot flush.

    The heartbeat file ``heartbeat_rank{rank}.json`` is rewritten
    atomically every ``interval`` seconds:

        {"rank": 0, "pid": 1234, "phase": "train", "step": 812,
         "progress_age_s": 0.4, "stalled": false, "uptime_s": 93.1,
         "written": 1754200000.0, "workers": {"1": {...}}}

    Freshness IS the health signal: a reader that finds ``written``
    older than ~3 intervals knows the process is gone or the GIL is
    held; ``progress_age_s``/``stalled`` separate alive-but-stuck from
    making-progress.  ``progress()`` is the hot-path call (a few plain
    attribute writes under a lock held for nanoseconds) — rules call it
    once per step."""

    def __init__(self, run_dir: str, rank: int = 0,
                 registry: MetricsRegistry | None = None,
                 interval: float = 5.0, stall_after: float = 60.0,
                 snapshot_path: str | None = None,
                 suffix: str | None = None):
        self.run_dir = run_dir
        self.rank = rank
        self.registry = registry
        self.interval = interval
        self.stall_after = stall_after
        self.snapshot_path = snapshot_path
        # ``suffix`` distinguishes co-located processes that are NOT
        # ranks of one training session (a tmserver next to a trainer
        # would otherwise both write heartbeat_rank0.json)
        self.path = os.path.join(
            run_dir, f"heartbeat_{suffix or f'rank{rank}'}.json")
        self._lock = threading.Lock()
        self._t_start = time.monotonic()
        self._phase = "startup"
        self._step: int | None = None
        self._last_progress = time.monotonic()
        self._workers: dict[str, dict] = {}
        self._stalled = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- hot path ------------------------------------------------------

    def progress(self, phase: str | None = None, step: int | None = None,
                 worker: int | None = None) -> None:
        """Record that work advanced.  ``worker`` scopes the update to
        one async-rule worker thread; rank-level phase/step otherwise."""
        now = time.monotonic()
        with self._lock:
            self._last_progress = now
            if phase is not None:
                # rank-level phase updates even for worker-scoped
                # progress: async-rule workers are the ONLY progress
                # source there, and a heartbeat stuck on 'startup'
                # after hours of training would misname every stall
                self._phase = phase
            if worker is None:
                if step is not None:
                    self._step = step
            else:
                w = self._workers.setdefault(str(worker), {})
                if phase is not None:
                    w["phase"] = phase
                if step is not None:
                    w["step"] = step
                w["progress_age_s"] = 0.0
                w["_last"] = now
            if self._stalled:
                self._stalled = False
                if self.registry is not None:
                    self.registry.inc("health/stall_recoveries_total")

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "HeartbeatReporter":
        os.makedirs(self.run_dir, exist_ok=True)
        self.write_once()  # a file exists from t=0, not t=interval
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"monitor-heartbeat-r{self.rank}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval)
            self._thread = None
        self.write_once()  # final state on disk

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self._check_stall()
            self.write_once()
            if self.registry is not None and self.snapshot_path:
                try:
                    self.registry.write_jsonl(self.snapshot_path)
                except OSError:
                    pass  # a full disk must not kill the training loop

    # -- watchdog ------------------------------------------------------

    def _check_stall(self) -> None:
        with self._lock:
            age = time.monotonic() - self._last_progress
            phase, step, was = self._phase, self._step, self._stalled
            if age > self.stall_after:
                self._stalled = True
        if age > self.stall_after and not was:
            # edge-triggered: name the stuck phase ONCE per episode
            print(f"[monitor] WATCHDOG rank {self.rank}: no progress for "
                  f"{age:.0f}s (phase={phase!r}, step={step}) — "
                  f"stall threshold {self.stall_after:.0f}s", file=sys.stderr,
                  flush=True)
            if self.registry is not None:
                self.registry.inc("health/stalls_total", phase=phase)

    # -- the file ------------------------------------------------------

    def state(self) -> dict:
        now = time.monotonic()
        with self._lock:
            workers = {
                k: {kk: vv for kk, vv in w.items() if kk != "_last"}
                | {"progress_age_s": round(now - w.get("_last", now), 3)}
                for k, w in self._workers.items()
            }
            return {
                "rank": self.rank,
                "pid": os.getpid(),
                "phase": self._phase,
                "step": self._step,
                "progress_age_s": round(now - self._last_progress, 3),
                "stalled": self._stalled,
                "uptime_s": round(now - self._t_start, 3),
                "written": time.time(),
                "workers": workers,
            }

    def write_once(self) -> str:
        try:
            atomic_write_text(self.path, json.dumps(self.state()))
        except OSError:
            pass
        return self.path


class StragglerDetector:
    """Rolling-median straggler detection over per-worker step times.

    ``observe(rank, seconds)`` returns True while ``rank`` is flagged:
    its own recent median exceeds ``factor`` x the median of the OTHER
    workers' recent steps.  The fleet median must exclude the
    candidate's own window — a pooled median would be dragged up by
    the straggler itself (with 2 equal windows a worker can never
    exceed ``factor`` x the pooled median, however slow it is).
    Needs ``min_samples`` observations from the flagged worker and at
    least 2 active workers before flagging (a solo worker has no peers
    to lag behind)."""

    def __init__(self, factor: float = 2.0, window: int = 32,
                 min_samples: int = 8,
                 registry: MetricsRegistry | None = None):
        self.factor = factor
        self.min_samples = min_samples
        self.registry = registry
        self._lock = threading.Lock()
        self._window = window
        self._times: dict[int, deque[float]] = {}
        self._flagged: set[int] = set()

    def observe(self, rank: int, seconds: float) -> bool:
        with self._lock:
            dq = self._times.setdefault(
                rank, deque(maxlen=self._window))
            dq.append(float(seconds))
            if len(self._times) < 2 or len(dq) < self.min_samples:
                return rank in self._flagged
            own = statistics.median(dq)
            others = [t for r, d in self._times.items()
                      if r != rank for t in d]
            peer_med = statistics.median(others)
            is_straggler = (peer_med > 0
                            and own > self.factor * peer_med)
            was = rank in self._flagged
            if is_straggler and not was:
                self._flagged.add(rank)
                if self.registry is not None:
                    self.registry.inc("health/straggler_flags_total",
                                      worker=rank)
                print(f"[monitor] STRAGGLER worker {rank}: median step "
                      f"{own * 1e3:.1f}ms vs peer median "
                      f"{peer_med * 1e3:.1f}ms "
                      f"(threshold {self.factor:g}x)",
                      file=sys.stderr, flush=True)
            elif not is_straggler and was:
                self._flagged.discard(rank)
            return is_straggler

    def stragglers(self) -> list[int]:
        with self._lock:
            return sorted(self._flagged)
