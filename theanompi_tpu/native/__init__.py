"""Native (C++) host-side kernels with build-on-demand ctypes bindings.

The reference's native muscle lived in its dependencies (Theano C/CUDA
codegen, HDF5, NCCL — SURVEY.md §2.12); the one genuinely host-bound
loop in this framework is the data pipeline's crop/flip/normalize, so
that is what gets a native implementation: ``augment.cpp`` fuses the
whole per-image transform into one pass (numpy needs a pad copy, a
fancy-index gather, an astype, and two broadcasted arithmetic passes —
five full-batch temporaries).  Measured on this host (single core,
256x 256px -> 224px crops): 186 ms vs 1025 ms per batch — 5.5x, while
staying BITWISE identical to numpy (same f32 op order); scales with
cores via the pthread fan-out on real multi-core hosts.

The shared object is compiled lazily with g++ the first time it is
needed and cached next to the source keyed by source mtime; every
caller must handle ``native_available() == False`` (no toolchain, or
the build failed) by falling back to the numpy path — data/utils.py
does this automatically.  Set ``THEANOMPI_TPU_NATIVE=0`` to force the
numpy path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "augment.cpp")
_SO = os.path.join(_DIR, "_augment.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> str:
    """Compile augment.cpp -> _augment.so if stale; returns .so path."""
    if (os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        return _SO
    tmp = _SO + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
           _SRC, "-o", tmp]
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    os.replace(tmp, _SO)  # atomic: concurrent builders race harmlessly
    return _SO


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("THEANOMPI_TPU_NATIVE", "1") == "0":
            return None
        try:
            lib = ctypes.CDLL(_build())
            lib.tm_native_abi_version.restype = ctypes.c_int
            if lib.tm_native_abi_version() != 2:
                return None
            lib.tm_crop_flip_normalize.restype = None
            lib.tm_crop_flip_normalize.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_float, ctypes.c_void_p,
                ctypes.c_int,
            ]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def native_available() -> bool:
    return _load() is not None


def crop_flip_normalize(
    images: np.ndarray,
    ys: np.ndarray,
    xs: np.ndarray,
    flips: np.ndarray,
    crop_h: int,
    crop_w: int,
    mean: np.ndarray,
    std: np.ndarray,
    divisor: float = 255.0,
    pad: int = 0,
    n_threads: int | None = None,
) -> np.ndarray:
    """Fused native crop+flip+normalize: out = ((px/divisor)-mean)/std
    with numpy's exact f32 op order (bitwise-matching the fallback).
    ``images`` uint8 NHWC; ``ys``/``xs`` int64 crop origins in padded
    coords; ``flips`` uint8; ``mean``/``std`` float32 per channel.
    Raises RuntimeError if the native library is unavailable — call
    ``native_available()`` first."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native augment library unavailable")
    images = np.ascontiguousarray(images)
    if images.dtype != np.uint8 or images.ndim != 4:
        raise ValueError(
            f"expected uint8 NHWC images, got {images.dtype} "
            f"ndim={images.ndim}")
    n, h, w, c = images.shape
    ys = np.ascontiguousarray(ys, np.int64)
    xs = np.ascontiguousarray(xs, np.int64)
    flips = np.ascontiguousarray(flips, np.uint8)
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    if mean.shape != (c,) or std.shape != (c,):
        raise ValueError(f"mean/std must have shape ({c},), got "
                         f"{mean.shape}/{std.shape}")
    if ys.shape != (n,) or xs.shape != (n,) or flips.shape != (n,):
        raise ValueError("ys/xs/flips must be per-image vectors")
    if (n and (ys.min() < 0 or xs.min() < 0
               or ys.max() > h + 2 * pad - crop_h
               or xs.max() > w + 2 * pad - crop_w)):
        raise ValueError(
            f"crop origins out of range for {h}x{w}+pad {pad} "
            f"crop {crop_h}x{crop_w}")
    out = np.empty((n, crop_h, crop_w, c), np.float32)
    if n_threads is None:
        n_threads = min(os.cpu_count() or 1, 8)
    lib.tm_crop_flip_normalize(
        images.ctypes.data, n, h, w, c, pad,
        ys.ctypes.data, xs.ctypes.data, flips.ctypes.data,
        crop_h, crop_w, mean.ctypes.data, std.ctypes.data,
        ctypes.c_float(divisor), out.ctypes.data, n_threads)
    return out
