// Fused host-side augmentation: random crop (with virtual reflect
// padding) + horizontal flip + affine normalize, uint8 NHWC -> float32.
//
// Native equivalent of the decode/augment half of the reference's
// parallel loader process (SURVEY.md §2.9/§3.4 — the reference leaned
// on HDF5/hickle C code plus numpy; here the whole per-image transform
// is ONE pass over the crop window, vs numpy's pad-copy + gather +
// astype + arithmetic chain, each a full-batch temporary).
//
// Built on demand by theanompi_tpu/native/__init__.py with g++ -O3;
// ctypes ABI, plain C signature, no Python.h dependency.

#include <cstdint>
#include <thread>
#include <vector>

namespace {

// numpy 'reflect' boundary (no edge repeat), applied repeatedly like
// np.pad does — handles pad >= n-1 (e.g. 4px pad on a 4px image).
inline int reflect(int i, int n) {
  if (n == 1) return 0;
  while (i < 0 || i >= n) {
    if (i < 0) i = -i;
    if (i >= n) i = 2 * n - 2 - i;
  }
  return i;
}

// The normalize arithmetic deliberately mirrors the numpy fallback's
// op sequence — f32 divide by `divisor`, subtract mean, divide by std
// — so the two paths are BITWISE identical (training runs must not
// depend on which implementation decoded the batch).
void run_range(const uint8_t* src, int h, int w, int c, int pad,
               const int64_t* ys, const int64_t* xs, const uint8_t* flips,
               int crop_h, int crop_w, const float* mean, const float* stdv,
               float divisor, float* dst, int begin, int end) {
  const int64_t img_stride = (int64_t)h * w * c;
  const int64_t out_stride = (int64_t)crop_h * crop_w * c;
  for (int i = begin; i < end; ++i) {
    const uint8_t* img = src + i * img_stride;
    float* out = dst + i * out_stride;
    const int y0 = (int)ys[i] - pad;  // offsets are in padded coords
    const int x0 = (int)xs[i] - pad;
    const bool flip = flips[i] != 0;
    for (int y = 0; y < crop_h; ++y) {
      const int sy = reflect(y0 + y, h);
      const uint8_t* row = img + (int64_t)sy * w * c;
      float* orow = out + (int64_t)y * crop_w * c;
      for (int x = 0; x < crop_w; ++x) {
        const int px = flip ? (crop_w - 1 - x) : x;
        const int sx = reflect(x0 + px, w);
        const uint8_t* p = row + (int64_t)sx * c;
        float* o = orow + (int64_t)x * c;
        for (int ch = 0; ch < c; ++ch)
          o[ch] = ((float)p[ch] / divisor - mean[ch]) / stdv[ch];
      }
    }
  }
}

}  // namespace

extern "C" {

// src: (n,h,w,c) uint8; ys/xs: per-image crop origin in PADDED
// coordinates, i.e. in [0, h+2*pad-crop_h]; flips: per-image 0/1;
// mean/stdv: per-channel, in (px/divisor) units;
// dst: (n,crop_h,crop_w,c) float32.
void tm_crop_flip_normalize(const uint8_t* src, int n, int h, int w, int c,
                            int pad, const int64_t* ys, const int64_t* xs,
                            const uint8_t* flips, int crop_h, int crop_w,
                            const float* mean, const float* stdv,
                            float divisor, float* dst, int n_threads) {
  if (n_threads <= 1 || n < 2 * n_threads) {
    run_range(src, h, w, c, pad, ys, xs, flips, crop_h, crop_w, mean, stdv,
              divisor, dst, 0, n);
    return;
  }
  std::vector<std::thread> ts;
  ts.reserve(n_threads);
  const int per = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    const int b = t * per;
    const int e = b + per < n ? b + per : n;
    if (b >= e) break;
    ts.emplace_back(run_range, src, h, w, c, pad, ys, xs, flips, crop_h,
                    crop_w, mean, stdv, divisor, dst, b, e);
  }
  for (auto& t : ts) t.join();
}

int tm_native_abi_version() { return 2; }

}  // extern "C"
