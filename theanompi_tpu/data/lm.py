"""Synthetic language-modeling dataset (for the sequence-parallel
transformer demo model).

The reference has no text pipeline (2016 CNN framework — SURVEY.md
§2.11); this dataset exists so the long-context path has a learnable
end-to-end training signal without network egress: sequences follow a
fixed random successor table (``next = table[tok]`` with probability
``1 - noise``, else uniform), so a causal LM can drive the loss toward
the table's conditional entropy.  Deterministic per (seed, epoch).

Yields ``(tokens, targets)`` of shape (B, seq_len) int32 with
``targets`` the one-step shift of the same underlying sequence —
computed BEFORE time-sharding, so sequence-parallel shards never need
cross-shard label traffic.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from theanompi_tpu.data.base import Batch, Dataset


class SeqLM_data(Dataset):
    def __init__(self, vocab: int = 256, seq_len: int = 128,
                 n_train: int = 4096, n_val: int = 512, seed: int = 0,
                 noise: float = 0.1):
        self.n_classes = vocab
        self.vocab = vocab
        self.seq_len = seq_len
        self.sample_shape = (seq_len,)
        self.n_train = n_train
        self.n_val = n_val
        self.seed = seed
        self.noise = noise
        rng = np.random.default_rng(seed)
        self.table = rng.permutation(vocab).astype(np.int32)

    def _gen(self, n: int, seed: int) -> tuple[np.ndarray, np.ndarray]:
        rng = np.random.default_rng(seed)
        seq = np.empty((n, self.seq_len + 1), np.int32)
        seq[:, 0] = rng.integers(0, self.vocab, n)
        for t in range(1, self.seq_len + 1):
            follow = rng.random(n) >= self.noise
            rand = rng.integers(0, self.vocab, n)
            seq[:, t] = np.where(follow, self.table[seq[:, t - 1]], rand)
        return seq[:, :-1], seq[:, 1:]

    def train_batches(self, epoch: int, global_batch: int,
                      rank: int = 0, size: int = 1) -> Iterator[Batch]:
        n = self.n_train_batches_for(epoch, global_batch, rank, size)
        for i in range(n):
            # batch content is a pure function of (seed, epoch, i, rank);
            # SeedSequence gives a portable, collision-resistant derivation
            # (builtin hash() is a CPython implementation detail)
            ss = np.random.SeedSequence([self.seed, epoch, i, rank])
            yield self._gen(global_batch, int(ss.generate_state(1)[0]))

    def val_batches(self, global_batch: int,
                    rank: int = 0, size: int = 1) -> Iterator[Batch]:
        for i in range(self.n_val_batches(global_batch)):
            yield self._gen(global_batch, self.seed + 10**9 + i)
