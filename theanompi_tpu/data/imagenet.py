"""ImageNet data object — sharded batch files + parallel loading.

Parity counterpart of the reference's ImageNet pipeline
(``theanompi/models/data/imagenet.py`` + its parallel hkl loader,
SURVEY.md §2.9/§3.4 — mount empty, no file:line).  The reference
pre-processed ImageNet into hickle (HDF5) batch files, sharded the
file list per rank, broadcast the epoch's shuffled order from rank 0,
and ran a separate loader process per worker that decoded the next
file into a shared buffer while the GPU trained.

TPU-native inversion of each piece:

* **hkl batch files → ``.npz`` shard files** (``train_*.npz`` /
  ``val_*.npz`` with uint8 ``x`` (N,H,W,3) and int ``y``).  Same
  pre-decoded-batch design — decode cost is paid once at preparation
  time, the training-time loader only reads + crops.
* **rank-0 broadcast of the shuffle → seeded permutation.**  The epoch
  order is a pure function of (seed, epoch), so every host computes
  the identical order with zero communication.
* **loader process + shared buffer → read-ahead thread feeding
  ``DevicePrefetcher``.**  File t+1 is decoded while file t's batches
  are consumed, and the prefetcher overlaps the sharded ``device_put``
  with the device step — the same double buffering without the process
  boundary (numpy releases the GIL for decode/copy).
* **no data present → deterministic synthetic mode** (this environment
  has no network egress): a small pool of class-conditional patterned
  images is generated once and sampled per batch, so benches and tests
  run the full pipeline (crop/flip/normalize/shard) with realistic
  shapes and clearly-labelled synthetic content.
"""

from __future__ import annotations

import glob
import os
import queue
import threading
from typing import Callable, Iterator, Sequence

import numpy as np

from theanompi_tpu.data.base import Batch, Dataset
from theanompi_tpu.data.utils import augment_normalize, center_normalize

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def readahead(items: Sequence, load: Callable, depth: int = 2) -> Iterator:
    """Yield ``load(item)`` for each item, decoding ``depth`` ahead in a
    background thread — the reference's parallel-loader overlap.

    Abandoning the generator (GC / ``close()``) stops the producer:
    its puts are timed and poll a stop event, so no thread or decoded
    shard is leaked when a consumer takes fewer batches than the files
    hold."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    sentinel = object()
    stop = threading.Event()
    err: list[BaseException] = []

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for it in items:
                if stop.is_set() or not put(load(it)):
                    return
        except BaseException as e:  # re-raised on the consumer side
            err.append(e)
        finally:
            put(sentinel)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            out = q.get()
            if out is sentinel:
                if err:
                    raise err[0]
                return
            yield out
    finally:
        stop.set()
        t.join(timeout=5)


# shard-size lookups are cached in-process and via an optional
# manifest.json so a real-ImageNet directory (~1000+ shard files) is
# not re-scanned per dataset instance (reference: per-rank loaders each
# enumerated the batch-file list once at startup too)
_SIZE_CACHE: dict[str, int] = {}


def _file_size_map(data_dir: str, files: list[str]) -> dict[str, int]:
    missing = [f for f in files if f not in _SIZE_CACHE]
    if missing:
        manifest = os.path.join(data_dir, "manifest.json")
        if os.path.exists(manifest):
            import json
            with open(manifest) as fh:
                m = json.load(fh)
            for f in missing:
                n = m.get(os.path.basename(f))
                if n is not None:
                    _SIZE_CACHE[f] = int(n)
            missing = [f for f in missing if f not in _SIZE_CACHE]
        for f in missing:
            with np.load(f) as z:
                _SIZE_CACHE[f] = len(z["y"])
    return {f: _SIZE_CACHE[f] for f in files}


def _synthetic_pool(n_images: int, n_classes: int, hw: int, seed: int):
    """Pool of distinct patterned images (uint8) + labels.  Classes get
    distinct low-frequency signatures so models can actually fit them."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:hw, 0:hw].astype(np.float32) / hw
    labels = (np.arange(n_images) * max(n_classes // max(n_images, 1), 1)
              ) % n_classes
    imgs = np.empty((n_images, hw, hw, 3), np.uint8)
    for i, c in enumerate(labels):
        fx, fy = 1 + c % 5, 1 + (c // 5) % 5
        phase = 2 * np.pi * (c % 97) / 97.0
        base = np.sin(2 * np.pi * fx * xx + phase) * np.cos(2 * np.pi * fy * yy)
        img = np.stack(
            [base * (0.5 + 0.5 * np.sin(phase + k)) for k in range(3)], -1
        )
        img = img + 0.3 * rng.standard_normal((hw, hw, 3), dtype=np.float32)
        imgs[i] = ((img - img.min()) / (img.max() - img.min() + 1e-8) * 255
                   ).astype(np.uint8)
    return imgs, labels.astype(np.int32)


class ImageNet_data(Dataset):
    """ImageNet batches from ``.npz`` shard files, or synthetic.

    ``data_dir`` layout: ``train_*.npz`` and ``val_*.npz``, each with
    ``x`` uint8 (N, store, store, 3) and ``y`` int labels.  Train
    images are randomly cropped ``store → crop`` + mirrored; val images
    are center-cropped.  File-list sharding over ``rank``/``size``
    reproduces the reference's per-rank shard lists for async rules and
    multi-host loading.
    """

    n_classes = 1000

    def __init__(self, data_dir: str | None = None, crop: int = 224,
                 seed: int = 0, synthetic_n: int = 8192,
                 synthetic_pool: int = 256, synthetic_store: int = 256,
                 readahead_depth: int = 2):
        self.crop = crop
        self.seed = seed
        self.sample_shape = (crop, crop, 3)
        self.readahead_depth = readahead_depth
        self.synthetic = False
        self.train_files: list[str] = []
        self.val_files: list[str] = []

        data_dir = data_dir or os.environ.get("THEANOMPI_TPU_IMAGENET")
        if data_dir and os.path.isdir(data_dir):
            self.train_files = sorted(glob.glob(os.path.join(data_dir, "train_*.npz")))
            self.val_files = sorted(glob.glob(os.path.join(data_dir, "val_*.npz")))

        if self.train_files:
            self._file_sizes = _file_size_map(
                data_dir, self.train_files + self.val_files)
            self.n_train = sum(self._file_sizes[f] for f in self.train_files)
            self.n_val = sum(self._file_sizes[f] for f in self.val_files)
        else:
            self.synthetic = True
            self.n_train = synthetic_n
            self.n_val = max(synthetic_n // 16, 256)
            self._pool_x, self._pool_y = _synthetic_pool(
                synthetic_pool, self.n_classes, synthetic_store, seed
            )

    # -- shared prep ---------------------------------------------------------

    def _prep_train(self, x: np.ndarray,
                    rng: np.random.Generator) -> np.ndarray:
        return augment_normalize(x, self.crop, self.crop, rng,
                                 mean=IMAGENET_MEAN, std=IMAGENET_STD)

    def _prep_val(self, x: np.ndarray) -> np.ndarray:
        return center_normalize(x, self.crop, self.crop,
                                mean=IMAGENET_MEAN, std=IMAGENET_STD)

    # -- synthetic path ------------------------------------------------------

    def _synthetic_batches(self, n_batches: int, global_batch: int,
                           rng: np.random.Generator, train: bool
                           ) -> Iterator[Batch]:
        pool = len(self._pool_x)
        for _ in range(n_batches):
            idx = rng.integers(0, pool, size=global_batch)
            x, y = self._pool_x[idx], self._pool_y[idx]
            if train:
                x = self._prep_train(x, rng)
            else:
                x = self._prep_val(x)
            yield x, y

    # -- file path -----------------------------------------------------------

    def _sharded_files(self, files: list[str], epoch: int | None,
                       rank: int, size: int) -> list[str]:
        files = list(files)
        if epoch is not None:
            order = np.random.default_rng(self.seed + 1000 + epoch)
            files = [files[i] for i in order.permutation(len(files))]
        if size > 1:
            files = files[rank::size]
        return files

    def _file_batches(self, files: list[str], global_batch: int,
                      aug_rng: np.random.Generator | None,
                      shuffle_rng: np.random.Generator | None
                      ) -> Iterator[Batch]:
        """Stream batches across shard files with read-ahead decode.
        Leftover tail samples of each file carry into the next batch."""

        def load(path):
            with np.load(path) as z:
                return z["x"], z["y"].astype(np.int32)

        buf_x: list[np.ndarray] = []
        buf_y: list[np.ndarray] = []
        buffered = 0
        for x, y in readahead(files, load, self.readahead_depth):
            if shuffle_rng is not None:
                p = shuffle_rng.permutation(len(y))
                x, y = x[p], y[p]
            buf_x.append(x)
            buf_y.append(y)
            buffered += len(y)
            while buffered >= global_batch:
                x_all = np.concatenate(buf_x) if len(buf_x) > 1 else buf_x[0]
                y_all = np.concatenate(buf_y) if len(buf_y) > 1 else buf_y[0]
                xb, yb = x_all[:global_batch], y_all[:global_batch]
                buf_x, buf_y = [x_all[global_batch:]], [y_all[global_batch:]]
                buffered -= global_batch
                if aug_rng is not None:
                    xb = self._prep_train(xb, aug_rng)
                else:
                    xb = self._prep_val(xb)
                yield xb, yb

    # -- Dataset interface ---------------------------------------------------

    def train_batches(self, epoch: int, global_batch: int,
                      rank: int = 0, size: int = 1) -> Iterator[Batch]:
        if self.synthetic:
            rng = np.random.default_rng(
                self.seed + 5000 + 7919 * epoch + 104729 * rank)
            n = (self.n_train // size) // global_batch
            yield from self._synthetic_batches(n, global_batch, rng, True)
            return
        files = self._sharded_files(self.train_files, epoch, rank, size)
        aug = np.random.default_rng(self.seed + 5000 + 7919 * epoch + rank)
        shuf = np.random.default_rng(self.seed + 9000 + 7919 * epoch + rank)
        yield from self._file_batches(files, global_batch, aug, shuf)

    def val_batches(self, global_batch: int,
                    rank: int = 0, size: int = 1) -> Iterator[Batch]:
        if self.synthetic:
            rng = np.random.default_rng(self.seed + 31337 + rank)
            n = (self.n_val // size) // global_batch
            yield from self._synthetic_batches(n, global_batch, rng, False)
            return
        files = self._sharded_files(self.val_files, None, rank, size)
        yield from self._file_batches(files, global_batch, None, None)

    def n_train_batches(self, global_batch: int) -> int:
        return self.n_train // global_batch

    def n_train_batches_for(self, epoch: int, global_batch: int,
                            rank: int = 0, size: int = 1) -> int:
        if self.synthetic:
            return (self.n_train // size) // global_batch
        files = self._sharded_files(self.train_files, epoch, rank, size)
        n_mine = sum(self._file_sizes[f] for f in files)
        return n_mine // global_batch


def prepare_imagenet_shards(src_images: np.ndarray, src_labels: np.ndarray,
                            out_dir: str, prefix: str = "train",
                            shard_size: int = 1024) -> list[str]:
    """Offline prep: pack (N,H,W,3) uint8 images + labels into
    ``{prefix}_NNNN.npz`` shard files — the rebuild's analogue of the
    reference's hickle pre-processing scripts (SURVEY.md §2.9)."""
    import json

    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for i in range(0, len(src_labels), shard_size):
        p = os.path.join(out_dir, f"{prefix}_{i // shard_size:04d}.npz")
        np.savez(p, x=src_images[i:i + shard_size],
                 y=src_labels[i:i + shard_size])
        paths.append(p)
    # maintain manifest.json so training-time init never scans shards
    manifest_path = os.path.join(out_dir, "manifest.json")
    manifest = {}
    if os.path.exists(manifest_path):
        with open(manifest_path) as fh:
            manifest = json.load(fh)
    for k, p in enumerate(paths):
        manifest[os.path.basename(p)] = int(
            min(shard_size, len(src_labels) - k * shard_size))
    with open(manifest_path, "w") as fh:
        json.dump(manifest, fh)
    return paths
