"""ImageNet data object — sharded batch files + parallel loading.

Parity counterpart of the reference's ImageNet pipeline
(``theanompi/models/data/imagenet.py`` + its parallel hkl loader,
SURVEY.md §2.9/§3.4 — mount empty, no file:line).  The reference
pre-processed ImageNet into hickle (HDF5) batch files, sharded the
file list per rank, broadcast the epoch's shuffled order from rank 0,
and ran a separate loader process per worker that decoded the next
file into a shared buffer while the GPU trained.

TPU-native inversion of each piece:

* **hkl batch files → shard files**: mmap-able ``train_*.x.npy`` /
  ``*.y.npy`` pairs (uint8 ``x`` (N,H,W,3), int ``y``) — the round-3
  default: zero decode at training time, the read-ahead thread just
  pages rows in (measured 1.8x the npz ingest rate on one core,
  tools/host_pipeline_probe.py) — with ``train_*.npz`` (round 1/2)
  still read.  Same pre-decoded design either way: decode cost is paid
  once at preparation time.
* **rank-0 broadcast of the shuffle → seeded permutation.**  The epoch
  order is a pure function of (seed, epoch), so every host computes
  the identical order with zero communication.
* **loader process + shared buffer → read-ahead thread feeding
  ``DevicePrefetcher``.**  File t+1 is decoded while file t's batches
  are consumed, and the prefetcher overlaps the sharded ``device_put``
  with the device step — the same double buffering without the process
  boundary (numpy releases the GIL for decode/copy).
* **no data present → deterministic synthetic mode** (this environment
  has no network egress): a small pool of class-conditional patterned
  images is generated once and sampled per batch, so benches and tests
  run the full pipeline (crop/flip/normalize/shard) with realistic
  shapes and clearly-labelled synthetic content.
"""

from __future__ import annotations

import glob
import json
import os
import queue
import threading
from typing import Callable, Iterator, Sequence

import numpy as np

from theanompi_tpu.data.base import Batch, Dataset
from theanompi_tpu.data.utils import augment_normalize, center_normalize

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def readahead(items: Sequence, load: Callable, depth: int = 2) -> Iterator:
    """Yield ``load(item)`` for each item, decoding ``depth`` ahead in a
    background thread — the reference's parallel-loader overlap.

    Abandoning the generator (GC / ``close()``) stops the producer:
    its puts are timed and poll a stop event, so no thread or decoded
    shard is leaked when a consumer takes fewer batches than the files
    hold."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    sentinel = object()
    stop = threading.Event()
    err: list[BaseException] = []

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for it in items:
                if stop.is_set() or not put(load(it)):
                    return
        except BaseException as e:  # re-raised on the consumer side
            err.append(e)
        finally:
            put(sentinel)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            out = q.get()
            if out is sentinel:
                if err:
                    raise err[0]
                return
            yield out
    finally:
        stop.set()
        t.join(timeout=5)


# shard-size lookups are cached in-process and via an optional
# manifest.json so a real-ImageNet directory (~1000+ shard files) is
# not re-scanned per dataset instance (reference: per-rank loaders each
# enumerated the batch-file list once at startup too)
_SIZE_CACHE: dict[str, int] = {}


def _file_size_map(data_dir: str, files: list[str]) -> dict[str, int]:
    missing = [f for f in files if f not in _SIZE_CACHE]
    if missing:
        manifest = os.path.join(data_dir, "manifest.json")
        if os.path.exists(manifest):
            import json
            with open(manifest) as fh:
                m = json.load(fh)
            for f in missing:
                n = m.get(os.path.basename(f))
                if n is not None:
                    _SIZE_CACHE[f] = int(n)
            missing = [f for f in missing if f not in _SIZE_CACHE]
        for f in missing:
            _SIZE_CACHE[f] = len(_load_shard(f)[1])
    return {f: _SIZE_CACHE[f] for f in files}


def _load_shard(path: str):
    """Decode one shard file.  ``*.x.npy`` pairs are the mmap-able
    format: ``np.load(mmap_mode='r')`` costs no decode and no copy —
    the OS pages image rows in as the gather touches them — which is
    what lets ONE host core assemble uint8 batches at device rate
    (tools/host_pipeline_probe.py measures both formats).  ``.npz``
    (zip container, member copy per load) remains supported.

    Cold-read strategy (round 5): ``posix_fadvise(WILLNEED)`` first —
    the kernel then streams the whole file at device speed (measured
    6 GB/s buffered on this box) instead of serving one page fault at
    a time (the bare strided touch measured 0.365 GB/s cold: QD-1
    faults, 16x under the device).  The strided touch AFTER the hint
    still (a) forces residency so the consumer's gather never blocks
    on I/O and (b) paces this read-ahead thread so ``readahead_depth``
    bounds memory, but it now walks pages the fadvise already landed."""
    if path.endswith(".x.npy"):
        x = np.load(path, mmap_mode="r")
        try:
            with open(path, "rb") as fh:
                os.posix_fadvise(fh.fileno(), 0, 0,
                                 os.POSIX_FADV_WILLNEED)
        except (AttributeError, OSError):  # pragma: no cover
            pass  # non-POSIX or odd fs: fall back to fault-driven I/O
        x.reshape(-1)[:: 4096].sum()  # one byte per page: residency
        return x, np.load(path[: -len(".x.npy")] + ".y.npy"
                          ).astype(np.int32)
    with np.load(path) as z:
        return z["x"], z["y"].astype(np.int32)


def _shard_glob(data_dir: str, prefix: str) -> list[str]:
    return sorted(
        glob.glob(os.path.join(data_dir, f"{prefix}_*.npz"))
        + glob.glob(os.path.join(data_dir, f"{prefix}_*.x.npy")))


# -- pure epoch-order derivation (shared with the ingest readers) -----------
#
# The reference broadcast each epoch's shuffled order from rank 0; here
# the order is a pure function of (seed, epoch, rank, size), so the
# in-process loader AND a standalone ingest reader fleet
# (theanompi_tpu/ingest) derive the identical stream with zero
# coordination — which is what makes the remote path byte-identical to
# the local one (pinned by tests/test_ingest.py).  These three helpers
# are THE single source of that derivation; ImageNet_data delegates.


def epoch_file_order(files: Sequence[str], seed: int, epoch: int | None,
                     rank: int = 0, size: int = 1) -> list[str]:
    """The epoch's sharded file list: seeded permutation of the full
    list (``epoch=None`` keeps sorted order — the val path), then this
    rank's ``[rank::size]`` slice."""
    files = list(files)
    if epoch is not None:
        order = np.random.default_rng(seed + 1000 + epoch)
        files = [files[i] for i in order.permutation(len(files))]
    if size > 1:
        files = files[rank::size]
    return files


def shuffle_rng(seed: int, epoch: int, rank: int) -> np.random.Generator:
    """The in-file shuffle stream: one per-file permutation is drawn
    from it per shard file, in epoch file order."""
    return np.random.default_rng(seed + 9000 + 7919 * epoch + rank)


def augment_rng(seed: int, epoch: int, rank: int) -> np.random.Generator:
    """The host-augmentation stream (unused — but still constructed —
    when augmentation runs on device)."""
    return np.random.default_rng(seed + 5000 + 7919 * epoch + rank)


def shard_tree_signature(train_files: Sequence[str],
                         sizes: dict[str, int], seed: int) -> dict:
    """Identity of a (shard set, seed) pair — what trainer and ingest
    reader must agree on for their streams to be byte-identical."""
    import hashlib

    sig = hashlib.sha256()
    for f in train_files:
        sig.update(f"{os.path.basename(f)}:{sizes[f]};".encode())
    return {"seed": int(seed),
            "n_train": int(sum(sizes[f] for f in train_files)),
            "n_files": len(train_files),
            "files_sha256": sig.hexdigest()}


def _synthetic_pool(n_images: int, n_classes: int, hw: int, seed: int):
    """Pool of distinct patterned images (uint8) + labels.  Classes get
    distinct low-frequency signatures so models can actually fit them."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:hw, 0:hw].astype(np.float32) / hw
    labels = (np.arange(n_images) * max(n_classes // max(n_images, 1), 1)
              ) % n_classes
    imgs = np.empty((n_images, hw, hw, 3), np.uint8)
    for i, c in enumerate(labels):
        fx, fy = 1 + c % 5, 1 + (c // 5) % 5
        phase = 2 * np.pi * (c % 97) / 97.0
        base = np.sin(2 * np.pi * fx * xx + phase) * np.cos(2 * np.pi * fy * yy)
        img = np.stack(
            [base * (0.5 + 0.5 * np.sin(phase + k)) for k in range(3)], -1
        )
        img = img + 0.3 * rng.standard_normal((hw, hw, 3), dtype=np.float32)
        imgs[i] = ((img - img.min()) / (img.max() - img.min() + 1e-8) * 255
                   ).astype(np.uint8)
    return imgs, labels.astype(np.int32)


class ImageNet_data(Dataset):
    """ImageNet batches from shard files, or synthetic.

    ``data_dir`` layout: ``train_*`` and ``val_*`` shards — mmap-able
    ``.x.npy``/``.y.npy`` pairs (the prep default) and/or ``.npz`` —
    with ``x`` uint8 (N, store, store, 3) and ``y`` int labels.  Train
    images are randomly cropped ``store → crop`` + mirrored; val images
    are center-cropped.  File-list sharding over ``rank``/``size``
    reproduces the reference's per-rank shard lists for async rules and
    multi-host loading.
    """

    n_classes = 1000

    def __init__(self, data_dir: str | None = None, crop: int = 224,
                 seed: int = 0, synthetic_n: int = 8192,
                 synthetic_pool: int = 256, synthetic_store: int = 256,
                 readahead_depth: int = 2,
                 augment_on_device: bool = False,
                 label_noise: float = 0.0):
        self.crop = crop
        self.seed = seed
        self.sample_shape = (crop, crop, 3)
        self.readahead_depth = readahead_depth
        # device-side crop/flip/normalize (ops/augment.py): the host
        # ships raw uint8 store images — 4x fewer H2D bytes, and the one
        # host core here cannot augment at device rate (~1600 img/s
        # native fused vs 2600+ img/s device step, measured round 2)
        self.augment_on_device = augment_on_device
        if augment_on_device:
            from theanompi_tpu.ops.augment import make_device_augment

            self.device_transform = make_device_augment(
                crop, mean=IMAGENET_MEAN, std=IMAGENET_STD)
        self.synthetic = False
        self.train_files: list[str] = []
        self.val_files: list[str] = []

        data_dir = data_dir or os.environ.get("THEANOMPI_TPU_IMAGENET")
        if data_dir and os.path.isdir(data_dir):
            self.train_files = _shard_glob(data_dir, "train")
            self.val_files = _shard_glob(data_dir, "val")

        if self.train_files:
            self._file_sizes = _file_size_map(
                data_dir, self.train_files + self.val_files)
            self.n_train = sum(self._file_sizes[f] for f in self.train_files)
            self.n_val = sum(self._file_sizes[f] for f in self.val_files)
            # prepared trees carry their label space (classes.json from
            # prepare_imagenet_from_images); without it keep the
            # ImageNet default of 1000 rather than guessing from labels
            # seen in shards (a subset scan could undercount)
            cj = os.path.join(data_dir, "classes.json")
            if os.path.exists(cj):
                with open(cj) as fh:
                    self.n_classes = len(json.load(fh))
        else:
            self.synthetic = True
            self.n_train = synthetic_n
            self.n_val = max(synthetic_n // 16, 256)
            self._pool_x, self._pool_y = _synthetic_pool(
                synthetic_pool, self.n_classes, synthetic_store, seed
            )
        # falsifiable-oracle knob (VERDICT r2 #5): synthetic labels are
        # re-flipped PER DRAW (pool images recur, so a fixed flip would
        # be memorizable); Bayes val-error floor is ρ·(C-1)/C in
        # expectation on every evaluation
        self.label_noise = float(label_noise)
        if label_noise > 0.0 and not self.synthetic:
            raise ValueError("label_noise is a synthetic-oracle knob; "
                             "real ImageNet shards were found and loaded")

    # -- shared prep ---------------------------------------------------------

    def _prep_train(self, x: np.ndarray,
                    rng: np.random.Generator) -> np.ndarray:
        if self.augment_on_device:
            return x  # raw uint8 store images; device crops/normalizes
        return augment_normalize(x, self.crop, self.crop, rng,
                                 mean=IMAGENET_MEAN, std=IMAGENET_STD)

    def _prep_val(self, x: np.ndarray) -> np.ndarray:
        if self.augment_on_device:
            return x
        return center_normalize(x, self.crop, self.crop,
                                mean=IMAGENET_MEAN, std=IMAGENET_STD)

    # -- synthetic path ------------------------------------------------------

    def _synthetic_batches(self, n_batches: int, global_batch: int,
                           rng: np.random.Generator, train: bool
                           ) -> Iterator[Batch]:
        pool = len(self._pool_x)
        for _ in range(n_batches):
            idx = rng.integers(0, pool, size=global_batch)
            x, y = self._pool_x[idx], self._pool_y[idx]
            if self.label_noise > 0.0:
                flip = rng.random(global_batch) < self.label_noise
                y = y.copy()
                y[flip] = rng.integers(0, self.n_classes,
                                       size=int(flip.sum()),
                                       dtype=np.int64).astype(y.dtype)
            if train:
                x = self._prep_train(x, rng)
            else:
                x = self._prep_val(x)
            yield x, y

    # -- file path -----------------------------------------------------------

    def _sharded_files(self, files: list[str], epoch: int | None,
                       rank: int, size: int) -> list[str]:
        return epoch_file_order(files, self.seed, epoch, rank, size)

    def _file_batches(self, files: list[str], global_batch: int,
                      aug_rng: np.random.Generator | None,
                      shuffle_rng: np.random.Generator | None
                      ) -> Iterator[Batch]:
        """Stream batches across shard files with read-ahead decode.
        Leftover tail samples of each file carry into the next batch.

        Each batch is assembled with ONE fancy-index gather per
        contributing shard, straight from the mmap — the only host
        copy an image takes before ``device_put``.  (The round-5
        in-session probe, tools/ingest_session_probe.py, found the
        previous shape of this loop — materialize ``x[perm]`` for the
        whole shard, then np.concatenate carried tails — cost ~3
        memcpy passes per image and capped a one-core host at ~1.4k
        img/s warm; the gather form is bit-identical in output: the
        same per-shard permutation sliced in the same order.)"""

        # pending: [x, y, perm, pos] — shard arrays (x usually a
        # mmap), its draw order, and how much of it is consumed.
        # (A reusable gather buffer was tried and rejected: on a
        # single-device CPU mesh jax.device_put may zero-copy ALIAS
        # host numpy memory, so reusing the buffer could corrupt an
        # in-flight staged batch — and the isolated profile showed
        # allocation is not the bottleneck.)
        pending: list[list] = []
        buffered = 0

        def assemble() -> Batch:
            x0 = pending[0][0]
            xb = np.empty((global_batch,) + x0.shape[1:], x0.dtype)
            parts_y: list[np.ndarray] = []
            need, at = global_batch, 0
            while need:
                x, y, perm, pos = pending[0]
                take = min(need, len(perm) - pos)
                sel = perm[pos:pos + take]
                np.take(x, sel, axis=0, out=xb[at:at + take])
                parts_y.append(y[sel])
                at += take
                need -= take
                if pos + take == len(perm):
                    pending.pop(0)
                else:
                    pending[0][3] = pos + take
            yb = parts_y[0] if len(parts_y) == 1 \
                else np.concatenate(parts_y)
            return xb, yb

        for x, y in readahead(files, _load_shard, self.readahead_depth):
            perm = (shuffle_rng.permutation(len(y))
                    if shuffle_rng is not None else np.arange(len(y)))
            pending.append([x, y, perm, 0])
            buffered += len(y)
            while buffered >= global_batch:
                xb, yb = assemble()
                buffered -= global_batch
                if aug_rng is not None:
                    xb = self._prep_train(xb, aug_rng)
                else:
                    xb = self._prep_val(xb)
                yield xb, yb

    # -- Dataset interface ---------------------------------------------------

    def train_batches(self, epoch: int, global_batch: int,
                      rank: int = 0, size: int = 1) -> Iterator[Batch]:
        if self.synthetic:
            rng = np.random.default_rng(
                self.seed + 5000 + 7919 * epoch + 104729 * rank)
            n = (self.n_train // size) // global_batch
            yield from self._synthetic_batches(n, global_batch, rng, True)
            return
        files = self._sharded_files(self.train_files, epoch, rank, size)
        aug = augment_rng(self.seed, epoch, rank)
        shuf = shuffle_rng(self.seed, epoch, rank)
        yield from self._file_batches(files, global_batch, aug, shuf)

    def val_batches(self, global_batch: int,
                    rank: int = 0, size: int = 1) -> Iterator[Batch]:
        if self.synthetic:
            rng = np.random.default_rng(self.seed + 31337 + rank)
            n = (self.n_val // size) // global_batch
            yield from self._synthetic_batches(n, global_batch, rng, False)
            return
        files = self._sharded_files(self.val_files, None, rank, size)
        yield from self._file_batches(files, global_batch, None, None)

    def n_train_batches(self, global_batch: int) -> int:
        return self.n_train // global_batch

    def n_train_batches_for(self, epoch: int, global_batch: int,
                            rank: int = 0, size: int = 1) -> int:
        if self.synthetic:
            return (self.n_train // size) // global_batch
        files = self._sharded_files(self.train_files, epoch, rank, size)
        n_mine = sum(self._file_sizes[f] for f in files)
        return n_mine // global_batch

    def ingest_signature(self) -> dict:
        """What a remote ingest reader must agree on for its stream to
        be byte-identical to this dataset's (theanompi_tpu/ingest):
        the seed (every rng above derives from it) and the exact shard
        set.  Compared against the reader's ``ingest_meta`` at
        RemoteBatchSource construction — a silent mismatch would train
        on a different permutation (or different data) while looking
        healthy."""
        if self.synthetic:
            raise RuntimeError(
                "synthetic datasets have no shard tree to serve "
                "remotely; distributed ingest needs a prepared "
                "data_dir (docs/DESIGN.md 'Distributed ingest')")
        return shard_tree_signature(self.train_files, self._file_sizes,
                                    self.seed)


def _update_manifest(out_dir: str, entries: dict[str, int]) -> None:
    """manifest.json maps shard basename -> sample count so
    training-time init never re-scans shard files."""
    import json

    manifest_path = os.path.join(out_dir, "manifest.json")
    manifest = {}
    if os.path.exists(manifest_path):
        with open(manifest_path) as fh:
            manifest = json.load(fh)
    manifest.update(entries)
    with open(manifest_path, "w") as fh:
        json.dump(manifest, fh)


def _write_shard(out_dir: str, prefix: str, index: int,
                 x: np.ndarray, y: np.ndarray, shard_format: str) -> str:
    """One shard in the chosen format; returns the path training
    discovers (for npy pairs, the ``.x.npy`` member)."""
    base = os.path.join(out_dir, f"{prefix}_{index:04d}")
    if shard_format == "npy":
        np.save(base + ".x.npy", x)
        np.save(base + ".y.npy", y)
        return base + ".x.npy"
    if shard_format == "npz":
        np.savez(base + ".npz", x=x, y=y)
        return base + ".npz"
    raise ValueError(f"unknown shard_format {shard_format!r} "
                     "(expected 'npy' or 'npz')")


def _unlink_shard(path: str) -> None:
    os.unlink(path)
    if path.endswith(".x.npy"):
        sibling = path[: -len(".x.npy")] + ".y.npy"
        if os.path.exists(sibling):
            os.unlink(sibling)


def _remove_shards(out_dir: str, paths, manifest: bool = True) -> None:
    """Delete shard files (incl. npy pair siblings); optionally prune
    their manifest entries."""
    paths = sorted(paths)
    if not paths:
        return
    if manifest:
        import json

        manifest_path = os.path.join(out_dir, "manifest.json")
        if os.path.exists(manifest_path):
            with open(manifest_path) as fh:
                m = json.load(fh)
            for p in paths:
                m.pop(os.path.basename(p), None)
            with open(manifest_path, "w") as fh:
                json.dump(m, fh)
    for p in paths:
        if os.path.exists(p):
            _unlink_shard(p)


def prepare_imagenet_shards(src_images: np.ndarray, src_labels: np.ndarray,
                            out_dir: str, prefix: str = "train",
                            shard_size: int = 1024,
                            shard_format: str = "npy") -> list[str]:
    """Offline prep: pack (N,H,W,3) uint8 images + labels into shard
    files — the rebuild's analogue of the reference's hickle
    pre-processing scripts (SURVEY.md §2.9).  Default format is the
    mmap-able ``.x.npy``/``.y.npy`` pair (see ``_load_shard``: training
    reads page in lazily with zero decode); ``shard_format='npz'``
    keeps the round-1/2 container.  A rerun replaces the prefix's
    previous shard set in EITHER format — training globs both, so a
    leftover would silently inflate the dataset."""
    os.makedirs(out_dir, exist_ok=True)
    preexisting = set(_shard_glob(out_dir, prefix))
    paths: list[str] = []
    try:
        for i in range(0, len(src_labels), shard_size):
            paths.append(_write_shard(out_dir, prefix, i // shard_size,
                                      src_images[i:i + shard_size],
                                      src_labels[i:i + shard_size],
                                      shard_format))
    except BaseException:
        _remove_shards(out_dir, set(paths) - preexisting, manifest=False)
        raise
    _remove_shards(out_dir, preexisting - set(paths))
    _update_manifest(out_dir, {
        os.path.basename(p): int(min(shard_size, len(src_labels) - k * shard_size))
        for k, p in enumerate(paths)})
    return paths


IMAGE_EXTENSIONS = (".jpeg", ".jpg", ".png", ".bmp", ".webp")


def list_image_dir(src_dir: str,
                   class_to_idx: dict[str, int] | None = None,
                   extensions: Sequence[str] = IMAGE_EXTENSIONS,
                   ) -> tuple[list[tuple[str, int]], dict[str, int]]:
    """Enumerate an ImageNet-style directory (one subdirectory per
    class, e.g. wnids) into (path, label) pairs.  Labels come from
    ``class_to_idx`` or the sorted subdirectory names — the same
    convention as the standard ImageFolder layout, so a real ImageNet
    train/ tree works unchanged."""
    classes = sorted(d for d in os.listdir(src_dir)
                     if os.path.isdir(os.path.join(src_dir, d)))
    if not classes:
        raise FileNotFoundError(
            f"{src_dir!r} has no class subdirectories (expected "
            "<src_dir>/<class>/<image>.jpeg, the ImageFolder layout)")
    if class_to_idx is None:
        class_to_idx = {c: i for i, c in enumerate(classes)}
    pairs = []
    for c in classes:
        if c not in class_to_idx:
            raise KeyError(f"directory {c!r} missing from class_to_idx")
        cdir = os.path.join(src_dir, c)
        for f in sorted(os.listdir(cdir)):
            if f.lower().endswith(tuple(extensions)):
                pairs.append((os.path.join(cdir, f), class_to_idx[c]))
    return pairs, class_to_idx


def decode_image(path: str, store: int) -> np.ndarray:
    """JPEG/PNG -> uint8 (store, store, 3): RGB, shorter side resized
    to ``store``, center crop — the reference's hickle prep stored
    256x256 center crops of the shorter-side-256 resize the same way
    (SURVEY.md §2.9)."""
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB")
        w, h = im.size
        scale = store / min(w, h)
        im = im.resize((max(store, round(w * scale)),
                        max(store, round(h * scale))), Image.BILINEAR)
        left = (im.width - store) // 2
        top = (im.height - store) // 2
        im = im.crop((left, top, left + store, top + store))
        return np.asarray(im, np.uint8)


def _bounded_thread_map(fn: Callable, items: Sequence, workers: int,
                        window: int) -> Iterator:
    """``ThreadPoolExecutor.map`` with BACKPRESSURE: at most ``window``
    decode results in flight, so a slow consumer (shard writes to a
    network fs) cannot make 1.28M decoded images pile up in RAM
    (``Executor.map`` submits everything eagerly; its ``chunksize`` is
    process-pool-only)."""
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=workers) as pool:
        pending: deque = deque()
        for item in items:
            pending.append(pool.submit(fn, item))
            if len(pending) >= window:
                yield pending.popleft().result()
        while pending:
            yield pending.popleft().result()


def prepare_imagenet_from_images(src_dir: str, out_dir: str,
                                 prefix: str = "train", store: int = 256,
                                 shard_size: int = 1024,
                                 class_to_idx: dict[str, int] | None = None,
                                 workers: int = 8,
                                 shuffle_seed: int | None = 0,
                                 shard_format: str = "npy") -> list[str]:
    """Raw image directory -> resized npz shards + manifest (VERDICT r1
    next-round #8): the full analogue of the reference's raw-JPEG hickle
    preparation.  Decodes in a thread pool (PIL releases the GIL in
    libjpeg), streams into fixed-size shards so ImageNet never has to
    fit in RAM, and records the class mapping in ``classes.json``.

    ``shuffle_seed`` shuffles the global file order once at prep time
    (class subdirectories are otherwise contiguous, which would make
    early training batches single-class even after training-time
    file-order shuffling); None keeps directory order.
    """
    import json

    try:
        import PIL  # noqa: F401
    except ImportError as e:  # pragma: no cover - PIL is in this env
        raise RuntimeError(
            "raw-image preparation needs Pillow; pre-decode with "
            "prepare_imagenet_shards(images, labels, ...) instead") from e

    pairs, class_to_idx = list_image_dir(src_dir, class_to_idx)
    if shuffle_seed is not None:
        order = np.random.default_rng(shuffle_seed).permutation(len(pairs))
        pairs = [pairs[i] for i in order]
    os.makedirs(out_dir, exist_ok=True)
    # note the previous run's shards now, remove the leftovers only
    # AFTER the new set is complete: a mid-run failure (one corrupt
    # JPEG) must not destroy an existing good dataset
    preexisting = set(_shard_glob(out_dir, prefix))
    with open(os.path.join(out_dir, "classes.json"), "w") as fh:
        json.dump(class_to_idx, fh)

    paths: list[str] = []
    counts: dict[str, int] = {}
    buf_x = np.empty((shard_size, store, store, 3), np.uint8)
    buf_y = np.empty(shard_size, np.int32)
    fill = 0

    def flush():
        nonlocal fill
        p = _write_shard(out_dir, prefix, len(paths), buf_x[:fill],
                         buf_y[:fill], shard_format)
        paths.append(p)
        counts[os.path.basename(p)] = fill
        fill = 0

    decoded = _bounded_thread_map(
        lambda pl: (decode_image(pl[0], store), pl[1]), pairs,
        workers=workers, window=workers * 4)
    try:
        for img, label in decoded:
            buf_x[fill] = img
            buf_y[fill] = label
            fill += 1
            if fill == shard_size:
                flush()
        if fill:
            flush()
    except BaseException:
        # mid-run failure (one corrupt JPEG): remove THIS run's new
        # shards so the directory still holds exactly the pre-run set —
        # without this, a cross-format rerun would leave a partial new
        # set beside the complete old one and training (which globs
        # both formats) would silently train on the union
        _remove_shards(out_dir, set(paths) - preexisting, manifest=False)
        raise
    # success: drop the previous run's leftover shards IN EITHER FORMAT
    # and prune their manifest entries
    _remove_shards(out_dir, preexisting - set(paths))
    _update_manifest(out_dir, counts)
    return paths
