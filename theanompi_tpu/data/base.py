"""Dataset contract.

Parity counterpart of the reference's data objects
(``theanompi/models/data/`` — per-rank shard lists, shuffled epoch
order broadcast from rank 0, train/val iterators; SURVEY.md §2.9 —
mount empty, no file:line).

TPU-native inversion: the reference gave each of N processes its own
shard and its own iterator.  Here one controller process yields
*global* batches (size ``batch_size * data_axis_size``) which
``shard_batch`` splits across the mesh in a single ``device_put`` —
the per-worker shard view becomes a sharding annotation.  The
``rank``/``size`` arguments survive for multi-host mode, where each
host process loads only its slice of the global batch.
"""

from __future__ import annotations

import abc
from typing import Iterator

import numpy as np

Batch = tuple[np.ndarray, np.ndarray]  # (images NHWC, integer labels)


class Dataset(abc.ABC):
    """Iterable source of global batches for one (model, run) pair."""

    #: per-shard sample shape, e.g. (32, 32, 3) — NHWC like XLA prefers
    sample_shape: tuple[int, ...]
    n_classes: int
    n_train: int
    n_val: int

    #: optional jittable ``transform(x, rng, train) -> fp32`` applied to
    #: each batch INSIDE the step (ops/augment.py).  When set, the host
    #: iterators yield raw (e.g. uint8 store-size) images and the device
    #: does crop/flip/normalize — honored by the default
    #: ``TpuModel.loss_fn``/``eval_fn``.
    device_transform = None

    @abc.abstractmethod
    def train_batches(
        self, epoch: int, global_batch: int, rank: int = 0, size: int = 1
    ) -> Iterator[Batch]:
        """Yield shuffled, augmented global train batches for ``epoch``.

        Shuffle order must be a pure function of ``epoch`` (the
        reference broadcast the epoch's shuffled file order from rank 0
        — deriving it from the epoch number gives every host the same
        order with no broadcast at all).
        """

    @abc.abstractmethod
    def val_batches(
        self, global_batch: int, rank: int = 0, size: int = 1
    ) -> Iterator[Batch]:
        """Yield validation batches in fixed order, no augmentation."""

    # -- multi-host (one controller process per host) -------------------

    @staticmethod
    def _block_slice(batch: Batch, host_rank: int, host_count: int) -> Batch:
        x, y = batch
        if len(x) % host_count != 0:
            raise ValueError(
                f"global batch {len(x)} not divisible by {host_count} hosts")
        chunk = len(x) // host_count
        sl = slice(host_rank * chunk, (host_rank + 1) * chunk)
        return x[sl], y[sl]

    def host_train_batches(self, epoch: int, global_batch: int,
                           host_rank: int, host_count: int) -> Iterator[Batch]:
        """This host's contiguous block of each *global* train batch.

        Multi-host BSP: ``jax.devices()`` orders devices by process, so
        host p's addressable shards cover rows
        ``[p*B/P, (p+1)*B/P)`` of every global batch;
        ``shard_batch`` reassembles the global array from these slices
        (``jax.make_array_from_process_local_data``).  Shuffle and
        augmentation order are pure functions of ``epoch`` (class
        docstring), so every host derives the identical global batch and
        the multi-host run is bit-equivalent to the single-process run.

        Default: build the global batch and slice — correct everywhere;
        datasets whose storage is row-addressable should override to
        read only their rows.
        """
        for batch in self.train_batches(epoch, global_batch):
            yield self._block_slice(batch, host_rank, host_count)

    def host_val_batches(self, global_batch: int, host_rank: int,
                         host_count: int) -> Iterator[Batch]:
        for batch in self.val_batches(global_batch):
            yield self._block_slice(batch, host_rank, host_count)

    def n_train_batches(self, global_batch: int) -> int:
        from theanompi_tpu.utils.helper_funcs import divide_batches

        return divide_batches(self.n_train, global_batch)

    def n_train_batches_for(self, epoch: int, global_batch: int,
                            rank: int = 0, size: int = 1) -> int:
        """EXACT number of batches ``train_batches(epoch, global_batch,
        rank, size)`` will yield.  Ranks' shards need not be equal
        (file-list sharding gives unequal sample counts), so training
        loops must size their iteration count with this, not with a
        global ``n_train / size`` estimate."""
        # default matches the index-sharding scheme (order[rank::size])
        n_mine = (self.n_train - rank + size - 1) // size
        return n_mine // global_batch

    def n_val_batches(self, global_batch: int) -> int:
        from theanompi_tpu.utils.helper_funcs import divide_batches

        return divide_batches(self.n_val, global_batch)
