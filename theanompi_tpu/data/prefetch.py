"""Double-buffered host->device prefetch.

TPU-native rebuild of the reference's parallel loader (a separate OS
process per worker decoding the next hkl file into a shared buffer
while the GPU trains — SURVEY.md §2.9/§3.4; mount empty, no file:line).

Here the decode/augment work runs in a background thread and the
staged result is already a *sharded device array* (``device_put`` with
a NamedSharding), so the H2D copy for batch t+1 overlaps the device
step for batch t — the same software double-buffering, minus the
process boundary and shared-memory plumbing (numpy releases the GIL
for the copy, and jax dispatch is async anyway).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator

import jax

from theanompi_tpu import monitor
from theanompi_tpu.parallel.mesh import shard_batch


class DevicePrefetcher:
    """Wrap a host batch iterator; yield mesh-sharded device batches.

    ``depth`` is the number of batches staged ahead (2 = classic double
    buffering).  The background thread dies with the iterator; call
    ``close()`` (or exhaust it) to stop early.

    ``stats`` exposes the loader's own critical path, measured inside
    the worker thread: ``busy_s`` is time spent assembling host
    batches + staging them to devices (NOT time blocked on a full
    queue), so ``images / busy_s`` is the sustained rate the loader
    could deliver if the consumer never ran — the in-session ingest
    number the round-4 verdict asked for, cleanly separated from
    device compute that shares the host core on CPU meshes.

    The same numbers are exported as ``ingest/loader_*`` monitor
    series (labelled ``source='local'|'remote'``), so a run fed by the
    in-process loader and one fed by a remote reader fleet
    (theanompi_tpu/ingest) are graphed on the same dashboard rows —
    docs/OBSERVABILITY.md.
    """

    _SENTINEL = object()

    def __init__(self, host_batches: Iterable, mesh, depth: int = 2,
                 spec=None, images_per_batch: int | None = None,
                 source: str = "local"):
        self.mesh = mesh
        self.spec = spec  # PartitionSpec override (default: data axis)
        self._source = source  # 'local' | 'remote' monitor label
        # stacked cadences (steps_per_call / grad_accum) stage
        # (k, global_batch, ...) leaves, where leaves[0].shape[0] is k,
        # not an image count — callers that stack must say how many
        # images one staged batch carries (models/base.py does)
        self._images_per_batch = images_per_batch
        self.stats = {"busy_s": 0.0, "batches": 0, "images": 0}
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err: BaseException | None = None
        self._thread = threading.Thread(
            target=self._worker, args=(iter(host_batches),), daemon=True
        )
        self._thread.start()

    def _worker(self, it: Iterator) -> None:
        import time

        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                staged = shard_batch(batch, self.mesh, self.spec)
                s = self.stats
                s["busy_s"] += time.perf_counter() - t0
                s["batches"] += 1
                if self._images_per_batch is not None:
                    s["images"] += self._images_per_batch
                else:
                    leaves = jax.tree.leaves(staged)
                    if leaves:
                        s["images"] += leaves[0].shape[0]
                if monitor.enabled():
                    # the loader-rate series local and remote ingest
                    # share (class docstring); strictly gated — the
                    # monitor-off hot path pays one branch
                    monitor.set_gauge("ingest/loader_img_s",
                                      s["images"] / s["busy_s"]
                                      if s["busy_s"] else 0.0,
                                      source=self._source)
                    monitor.set_gauge("ingest/loader_queue_depth",
                                      self._q.qsize(),
                                      source=self._source)
                    monitor.inc("ingest/loader_batches_total",
                                source=self._source)
                while not self._stop.is_set():
                    try:
                        self._q.put(staged, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surfaced to the consumer thread
            self._err = e
        finally:
            while not self._stop.is_set():
                try:
                    self._q.put(self._SENTINEL, timeout=0.1)
                    return
                except queue.Full:
                    continue

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._SENTINEL:
            self._thread.join()
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self) -> None:
        self._stop.set()
        # drain so the worker unblocks
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
