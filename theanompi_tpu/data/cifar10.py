"""CIFAR-10 data object.

Parity counterpart of the reference's in-memory CIFAR-10 loader
(``theanompi/models/data/cifar10.py``, SURVEY.md §2.9 — mount empty,
no file:line).

Loads the standard python-pickled CIFAR-10 batches from
``data_dir`` (``cifar-10-batches-py``) or an ``cifar10.npz`` file with
arrays ``x_train/y_train/x_test/y_test``.  This environment has no
network egress, so when no data is found the loader falls back to a
deterministic *synthetic* CIFAR-shaped dataset (class-conditional
Gaussian blobs + structured patterns) — learnable, so smoke runs and
tests show real convergence, and clearly labelled as synthetic.
"""

from __future__ import annotations

import os
import pickle
from typing import Iterator

import numpy as np

from theanompi_tpu.data.base import Batch, Dataset
from theanompi_tpu.data.utils import augment_normalize, center_normalize

CIFAR_MEAN = (0.4914, 0.4822, 0.4465)
CIFAR_STD = (0.2470, 0.2435, 0.2616)


def _load_pickled_batches(d: str):
    xs, ys = [], []
    for i in range(1, 6):
        with open(os.path.join(d, f"data_batch_{i}"), "rb") as f:
            b = pickle.load(f, encoding="bytes")
        xs.append(b[b"data"])
        ys.append(b[b"labels"])
    with open(os.path.join(d, "test_batch"), "rb") as f:
        b = pickle.load(f, encoding="bytes")
    x_train = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    x_test = np.asarray(b[b"data"]).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return (x_train, np.concatenate(ys).astype(np.int32),
            x_test, np.asarray(b[b"labels"], np.int32))


def _synthetic_cifar(n_train: int, n_val: int, n_classes: int = 10,
                     seed: int = 0, hw: int = 32,
                     label_noise: float = 0.0):
    """Deterministic learnable stand-in: each class is a distinct
    low-frequency pattern + noise, so a small CNN separates them.

    ``label_noise`` makes the oracle FALSIFIABLE (VERDICT r2 #5): each
    label is replaced by a uniform class draw with probability ρ, so
    the Bayes-optimal val error has a computable nonzero floor
    ρ·(C-1)/C — a model below the floor is cheating (leaky oracle), a
    model stuck above it regressed.  Train and val are DISJOINT draws
    (different sub-seeds) with independent noise: memorizing train
    noise cannot move val off its floor.  Returns the realized
    flipped-to-wrong-class masks so tests can assert against the exact
    floor, not just its expectation."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:hw, 0:hw].astype(np.float32) / hw
    protos = []
    for c in range(n_classes):
        fx, fy = 1 + c % 3, 1 + (c // 3) % 3
        phase = 2 * np.pi * c / n_classes
        base = np.sin(2 * np.pi * fx * xx + phase) * np.cos(2 * np.pi * fy * yy)
        chan = np.stack([base * (0.5 + 0.5 * np.sin(phase + k)) for k in range(3)], -1)
        protos.append(chan.astype(np.float32))
    protos = np.stack(protos)  # (C, H, W, 3)

    def make(n, seed_off):
        r = np.random.default_rng(seed + seed_off)
        y_true = r.integers(0, n_classes, size=n).astype(np.int32)
        x = protos[y_true] + 0.35 * r.standard_normal((n, hw, hw, 3),
                                                      dtype=np.float32)
        x = ((x - x.min()) / (x.max() - x.min()) * 255).astype(np.uint8)
        y = y_true.copy()
        if label_noise > 0.0:
            flip = r.random(n) < label_noise
            y[flip] = r.integers(0, n_classes, size=int(flip.sum()),
                                 dtype=np.int32)
        return x, y, (y != y_true)

    x_tr, y_tr, wrong_tr = make(n_train, 1)
    x_va, y_va, wrong_va = make(n_val, 2)
    return x_tr, y_tr, x_va, y_va, wrong_tr, wrong_va


class Cifar10_data(Dataset):
    sample_shape = (32, 32, 3)
    n_classes = 10

    def __init__(self, data_dir: str | None = None, synthetic_n: int = 4096,
                 crop: int = 32, pad: int = 4, seed: int = 0,
                 augment_on_device: bool = False,
                 label_noise: float = 0.0):
        self.crop = crop
        self.pad = pad
        self.seed = seed
        self.synthetic = False
        # device-side pad/crop/flip/normalize (ops/augment.py) — the
        # host then only gathers uint8 rows; same economics as the
        # ImageNet path (data/imagenet.py)
        self.augment_on_device = augment_on_device
        if augment_on_device:
            from theanompi_tpu.ops.augment import make_device_augment

            self.device_transform = make_device_augment(
                crop, mean=self.mean, std=self.std, pad=pad)

        candidates = []
        if data_dir:
            candidates += [data_dir, os.path.join(data_dir, "cifar-10-batches-py")]
        env = os.environ.get("THEANOMPI_TPU_DATA")
        if env:
            candidates += [os.path.join(env, "cifar-10-batches-py"),
                           os.path.join(env, "cifar10.npz")]

        loaded = None
        for cand in candidates:
            if cand.endswith(".npz") and os.path.exists(cand):
                with np.load(cand) as z:
                    loaded = (z["x_train"], z["y_train"].astype(np.int32),
                              z["x_test"], z["y_test"].astype(np.int32))
                break
            if os.path.isdir(cand) and os.path.exists(
                os.path.join(cand, "data_batch_1")
            ):
                loaded = _load_pickled_batches(cand)
                break

        #: realized fraction of labels differing from the true class —
        #: 0.0 for real data (no injected noise by construction)
        self.train_noise_frac = 0.0
        self.val_noise_frac = 0.0
        if loaded is None:
            self.synthetic = True
            (*loaded, wrong_tr, wrong_va) = _synthetic_cifar(
                synthetic_n, max(synthetic_n // 8, 256), seed=seed,
                label_noise=label_noise)
            # the EXACT val-error floor for a Bayes-optimal model
            # (tests assert against this, not just ρ·(C-1)/C)
            self.train_noise_frac = float(wrong_tr.mean())
            self.val_noise_frac = float(wrong_va.mean())
        elif label_noise > 0.0:
            raise ValueError("label_noise is a synthetic-oracle knob; "
                             "real CIFAR data was found and loaded")
        self.x_train, self.y_train, self.x_val, self.y_val = loaded
        self.n_train = len(self.x_train)
        self.n_val = len(self.x_val)
        if crop != 32:
            self.sample_shape = (crop, crop, 3)

    #: normalization constants in [0,1] units; subclasses override
    #: (e.g. the WGAN's tanh-range prep uses mean=std=0.5)
    mean = CIFAR_MEAN
    std = CIFAR_STD

    def train_batches(self, epoch: int, global_batch: int,
                      rank: int = 0, size: int = 1) -> Iterator[Batch]:
        order = np.random.default_rng(self.seed + 1000 + epoch).permutation(self.n_train)
        if size > 1:
            # async-rule mode: every worker sees a disjoint shard (the
            # reference's per-rank file-list sharding, SURVEY.md §2.9)
            order = order[rank::size]
        aug_rng = np.random.default_rng(self.seed + 5000 + 7919 * epoch + rank)
        n = len(order) // global_batch
        for i in range(n):
            idx = order[i * global_batch:(i + 1) * global_batch]
            if self.augment_on_device:
                yield self.x_train[idx], self.y_train[idx]
                continue
            x = augment_normalize(self.x_train[idx], self.crop, self.crop,
                                  aug_rng, pad=self.pad, mean=self.mean,
                                  std=self.std)
            yield x, self.y_train[idx]

    def val_batches(self, global_batch: int,
                    rank: int = 0, size: int = 1) -> Iterator[Batch]:
        n = self.n_val_batches(global_batch)
        for i in range(n):
            sl = slice(i * global_batch, (i + 1) * global_batch)
            if self.augment_on_device:
                yield self.x_val[sl], self.y_val[sl]
                continue
            yield center_normalize(self.x_val[sl], self.crop, self.crop,
                                   mean=self.mean, std=self.std), self.y_val[sl]
