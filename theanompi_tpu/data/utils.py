"""Host-side augmentation: random crop + horizontal mirror + normalize.

Parity with the reference's on-the-fly crop/flip in its parallel
loader (``theanompi/models/data/utils.py`` per SURVEY.md §2.9/§3.4 —
mount empty, no file:line).  Two implementations with identical
randomness and results:

* the fused native C++ kernel (theanompi_tpu/native) — one pass per
  image, used automatically for uint8 input when the lazy g++ build
  succeeded;
* vectorised numpy (pad copy + gather + astype + arithmetic), the
  portable fallback and the oracle the native path is tested against.

Either way the work stays on host so the device step is static-shaped.
"""

from __future__ import annotations

import numpy as np

from theanompi_tpu import native


def _gather_crops(images, ys, xs, flips, crop_h, crop_w, pad):
    """Pad-gather-flip in numpy (the oracle for the native kernel):
    reflect-pad, strided fancy-index gather of each crop window, then
    mirror the flipped subset."""
    n = images.shape[0]
    if pad:
        images = np.pad(
            images, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect"
        )
    rows = ys[:, None, None] + np.arange(crop_h)[None, :, None]
    cols = xs[:, None, None] + np.arange(crop_w)[None, None, :]
    out = images[np.arange(n)[:, None, None], rows, cols]
    out[flips] = out[flips, :, ::-1]
    return out


def random_crop_flip(
    images: np.ndarray,
    crop_h: int,
    crop_w: int,
    rng: np.random.Generator,
    flip: bool = True,
    pad: int = 0,
) -> np.ndarray:
    """Random-crop each NHWC image to (crop_h, crop_w) and mirror half.

    ``pad`` reflects-pads H/W first (CIFAR-style 4-px padding).  When
    the image already equals the crop size and pad=0, only flips apply.
    """
    n, h, w, _ = images.shape
    ph, pw = h + 2 * pad, w + 2 * pad
    if ph < crop_h or pw < crop_w:
        raise ValueError(f"images {ph}x{pw} smaller than crop {crop_h}x{crop_w}")
    ys = rng.integers(0, ph - crop_h + 1, size=n)
    xs = rng.integers(0, pw - crop_w + 1, size=n)
    flips = (rng.random(n) < 0.5) if flip else np.zeros(n, bool)
    return np.ascontiguousarray(
        _gather_crops(images, ys, xs, flips, crop_h, crop_w, pad))


def center_crop(images: np.ndarray, crop_h: int, crop_w: int) -> np.ndarray:
    _, h, w, _ = images.shape
    y0, x0 = (h - crop_h) // 2, (w - crop_w) // 2
    return np.ascontiguousarray(images[:, y0:y0 + crop_h, x0:x0 + crop_w])


def normalize(images: np.ndarray, mean, std) -> np.ndarray:
    mean = np.asarray(mean, np.float32).reshape(1, 1, 1, -1)
    std = np.asarray(std, np.float32).reshape(1, 1, 1, -1)
    return (images.astype(np.float32) - mean) / std


def _mean_std(c: int, mean, std):
    m = np.zeros(c, np.float32) if mean is None else np.asarray(mean, np.float32)
    s = np.ones(c, np.float32) if std is None else np.asarray(std, np.float32)
    return m, s


def _use_native(images: np.ndarray) -> bool:
    return images.dtype == np.uint8 and native.native_available()


def augment_normalize(
    images: np.ndarray,
    crop_h: int,
    crop_w: int,
    rng: np.random.Generator,
    *,
    flip: bool = True,
    pad: int = 0,
    mean=None,
    std=None,
    divisor: float = 255.0,
) -> np.ndarray:
    """Random crop (reflect ``pad``) + mirror-half + normalize, fused.

    Randomness is drawn up front in a fixed order, so native and numpy
    paths produce IDENTICAL batches for the same ``rng`` state (and the
    draw order matches the historical ``random_crop_flip``).
    """
    n, h, w, c = images.shape
    ph, pw = h + 2 * pad, w + 2 * pad
    if ph < crop_h or pw < crop_w:
        raise ValueError(f"images {ph}x{pw} smaller than crop {crop_h}x{crop_w}")
    ys = rng.integers(0, ph - crop_h + 1, size=n)
    xs = rng.integers(0, pw - crop_w + 1, size=n)
    flips = (rng.random(n) < 0.5) if flip else np.zeros(n, bool)
    if _use_native(images):
        m, s = _mean_std(c, mean, std)
        return native.crop_flip_normalize(images, ys, xs, flips, crop_h,
                                          crop_w, m, s, divisor=divisor,
                                          pad=pad)
    out = _gather_crops(images, ys, xs, flips, crop_h, crop_w, pad)
    out = out.astype(np.float32) / divisor
    if mean is not None or std is not None:
        out = normalize(out, *_mean_std(c, mean, std))
    return np.ascontiguousarray(out)


def center_normalize(
    images: np.ndarray,
    crop_h: int,
    crop_w: int,
    *,
    mean=None,
    std=None,
    divisor: float = 255.0,
) -> np.ndarray:
    """Deterministic center crop + normalize (validation path)."""
    n, h, w, c = images.shape
    if h < crop_h or w < crop_w:
        raise ValueError(f"images {h}x{w} smaller than crop {crop_h}x{crop_w}")
    y0, x0 = (h - crop_h) // 2, (w - crop_w) // 2
    if _use_native(images):
        m, s = _mean_std(c, mean, std)
        ys = np.full(n, y0, np.int64)
        xs = np.full(n, x0, np.int64)
        return native.crop_flip_normalize(images, ys, xs,
                                          np.zeros(n, np.uint8), crop_h,
                                          crop_w, m, s, divisor=divisor,
                                          pad=0)
    out = center_crop(images, crop_h, crop_w).astype(np.float32) / divisor
    if mean is not None or std is not None:
        out = normalize(out, *_mean_std(c, mean, std))
    return out
