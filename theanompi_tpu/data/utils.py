"""Host-side augmentation: random crop + horizontal mirror.

Parity with the reference's on-the-fly crop/flip in its parallel
loader (``theanompi/models/data/utils.py`` per SURVEY.md §2.9/§3.4 —
mount empty, no file:line).  Vectorised numpy over the whole batch
(the reference looped per image in its loader process); kept on host
so the device step stays static-shaped.
"""

from __future__ import annotations

import numpy as np


def random_crop_flip(
    images: np.ndarray,
    crop_h: int,
    crop_w: int,
    rng: np.random.Generator,
    flip: bool = True,
    pad: int = 0,
) -> np.ndarray:
    """Random-crop each NHWC image to (crop_h, crop_w) and mirror half.

    ``pad`` reflects-pads H/W first (CIFAR-style 4-px padding).  When
    the image already equals the crop size and pad=0, only flips apply.
    """
    n, h, w, c = images.shape
    if pad:
        images = np.pad(
            images, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect"
        )
        h, w = h + 2 * pad, w + 2 * pad
    if h < crop_h or w < crop_w:
        raise ValueError(f"images {h}x{w} smaller than crop {crop_h}x{crop_w}")

    ys = rng.integers(0, h - crop_h + 1, size=n)
    xs = rng.integers(0, w - crop_w + 1, size=n)
    # gather crops via strided fancy indexing (one pass, no python loop)
    rows = ys[:, None, None] + np.arange(crop_h)[None, :, None]
    cols = xs[:, None, None] + np.arange(crop_w)[None, None, :]
    out = images[np.arange(n)[:, None, None], rows, cols]

    if flip:
        mask = rng.random(n) < 0.5
        out[mask] = out[mask, :, ::-1]
    return np.ascontiguousarray(out)


def center_crop(images: np.ndarray, crop_h: int, crop_w: int) -> np.ndarray:
    _, h, w, _ = images.shape
    y0, x0 = (h - crop_h) // 2, (w - crop_w) // 2
    return np.ascontiguousarray(images[:, y0:y0 + crop_h, x0:x0 + crop_w])


def normalize(images: np.ndarray, mean, std) -> np.ndarray:
    mean = np.asarray(mean, np.float32).reshape(1, 1, 1, -1)
    std = np.asarray(std, np.float32).reshape(1, 1, 1, -1)
    return (images.astype(np.float32) - mean) / std
