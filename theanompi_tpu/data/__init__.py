from theanompi_tpu.data.base import Batch, Dataset
from theanompi_tpu.data.cifar10 import Cifar10_data
from theanompi_tpu.data.prefetch import DevicePrefetcher
from theanompi_tpu.data.utils import (
    augment_normalize,
    center_crop,
    center_normalize,
    normalize,
    random_crop_flip,
)

__all__ = [
    "Batch", "Dataset", "Cifar10_data", "DevicePrefetcher",
    "augment_normalize", "center_normalize",
    "random_crop_flip", "center_crop", "normalize",
]
