from theanompi_tpu.data.base import Batch, Dataset
from theanompi_tpu.data.cifar10 import Cifar10_data
from theanompi_tpu.data.prefetch import DevicePrefetcher
from theanompi_tpu.data.utils import center_crop, normalize, random_crop_flip

__all__ = [
    "Batch", "Dataset", "Cifar10_data", "DevicePrefetcher",
    "random_crop_flip", "center_crop", "normalize",
]
