"""BSP — synchronous data-parallel training.

Parity rebuild of the reference's BSP worker process (SURVEY.md §2.3,
§3.2 — mount empty, no file:line): per-iteration train step +
gradient allreduce, per-epoch validation, ``adjust_hyperp``, rank-0
checkpoint.  Here the N worker processes collapse into one SPMD
program over the mesh's ``data`` axis; the exchange is fused into the
jitted step (parallel/bsp.py), so this module is just the epoch
driver: data staging, validation, LR schedule, checkpoint/resume,
recorder bookkeeping.
"""

from __future__ import annotations

import os
import time

from theanompi_tpu import monitor
from theanompi_tpu.models.base import TpuModel
from theanompi_tpu.parallel.mesh import data_mesh
from theanompi_tpu.rules.base import Rule, resolve_model_class
from theanompi_tpu.utils.checkpoint import Checkpointer
from theanompi_tpu.utils.profiling import StepProfiler
from theanompi_tpu.utils.recorder import Recorder


def run_bsp_session(model: TpuModel, sync_type: str = "avg",
                    resume: bool = False, recorder: Recorder | None = None,
                    max_epochs: int | None = None,
                    checkpoint: bool = True,
                    profile_dir: str | None = None,
                    monitor_dir: str | None = None) -> dict:
    """The BSP epoch loop (callable directly, e.g. from the launcher).

    ``profile_dir`` (or env ``THEANOMPI_TPU_PROFILE``) captures a
    jax.profiler trace of the first steps — utils/profiling.py.
    ``monitor_dir`` (or env ``THEANOMPI_TPU_MONITOR``) activates the
    telemetry subsystem: step-time histogram, per-phase spans,
    heartbeat/watchdog, and a postmortem dump if the loop dies
    (docs/OBSERVABILITY.md)."""
    cfg = model.config
    # multi-host: rank = host index, so only host 0 prints / writes the
    # JSONL curve (the reference's rank-0 gating, SURVEY.md §3.5)
    host = model.host_rank
    recorder = recorder or Recorder(
        rank=host, size=model.n_workers, print_freq=cfg.print_freq,
        save_dir=cfg.snapshot_dir if host == 0 else None,
        flops_per_sample=model.train_flops_per_sample)
    profiler = StepProfiler(profile_dir)
    with monitor.session(monitor_dir, rank=host):
        monitor.progress(phase="compile")
        with monitor.span("bsp/compile"):
            model.compile_iter_fns(sync_type)

        ckpt = None
        start_epoch = 0
        if checkpoint:
            ckpt = Checkpointer(os.path.join(cfg.snapshot_dir, model.name))
            if resume:
                # integrity-checked resume (resilience.recovery): a
                # corrupt latest checkpoint falls back to the previous
                # kept epoch instead of killing the restart
                _, payload = ckpt.restore_latest_verified(like={
                    "state": model.state, "epoch": 0})
                if payload is not None:
                    # re-establish the model's sharding (a TP model would
                    # otherwise train on replicated restored arrays)
                    model.state = model.adopt_restored_state(
                        payload["state"])
                    start_epoch = int(payload["epoch"]) + 1
                    recorder.load(cfg.snapshot_dir)
                    # fast-forward the LR schedule (reference resume
                    # semantics)
                    model.adjust_hyperp(start_epoch)

        n_epochs = model.n_epochs if max_epochs is None else min(
            model.n_epochs, start_epoch + max_epochs)
        last_val: dict = {}
        with profiler:  # __exit__ stops the trace even on a crash
            try:
                for epoch in range(start_epoch, n_epochs):
                    # the epoch number rides the heartbeat (progress
                    # below) and this gauge, NOT a span label — a
                    # per-epoch label would shatter span_ms into one
                    # series per epoch
                    monitor.set_gauge("bsp/epoch", epoch)
                    with monitor.span("bsp/epoch"):
                        n_iters = model.begin_epoch(epoch)
                        it = 0
                        k = max(getattr(model.config, "steps_per_call", 1),
                                getattr(model.config, "grad_accum_steps", 1))
                        while it < n_iters:
                            # covers steps_per_call iterations per dispatch
                            t0 = time.monotonic()
                            consumed = model.train_iter(it, recorder)
                            if consumed is None:
                                # legacy override that returns nothing —
                                # only valid when each call consumes
                                # exactly one batch
                                if k > 1:
                                    raise RuntimeError(
                                        f"{type(model).__name__}.train_iter"
                                        " returned None with a stacked "
                                        "cadence (steps_per_call or "
                                        "grad_accum_steps > 1); it must "
                                        "return the number of iterations "
                                        "consumed")
                                consumed = 1
                            it += consumed
                            # per-iteration time (dispatch wall / iters
                            # covered); over a pipelined epoch the mean is
                            # honest because dispatch backpressure tracks
                            # device time
                            monitor.observe_step(
                                (time.monotonic() - t0) / consumed,
                                phase="train", step=it)
                            profiler.step()  # trace spans epochs until
                            # n_steps hit
                        model._flush_metrics(recorder)
                        monitor.progress(phase="validate")
                        with monitor.span("bsp/validate"):
                            last_val = model.val_epoch(recorder)
                            # times itself ('calc')
                        model.adjust_hyperp(epoch + 1)
                        if ckpt is not None:
                            monitor.progress(phase="checkpoint")
                            with monitor.span("bsp/checkpoint"):
                                ckpt.save(epoch, {"state": model.state,
                                                  "epoch": epoch})
                        recorder.epoch_summary(epoch, last_val.get("loss"),
                                               last_val.get("error"))
                        monitor.progress(phase="epoch_end", step=epoch)
            finally:
                model.cleanup()  # also on failure: stops the prefetcher
                if ckpt is not None:
                    ckpt.close()
    return {"val": last_val, "epochs_run": n_epochs - start_epoch,
            "records": recorder.epoch_records}


class BSP(Rule):
    """Synchronous BSP data-parallel rule (reference rule #1).

    ``model_parallel``/``seq_parallel`` carve those axes out of the
    device set (remaining devices go to ``data``) so tensor-parallel
    models (``transformer_lm_tp``) and sequence-parallel runs are
    reachable from the launcher, not just from Python."""

    name = "BSP"
    uses_global_mesh = True

    def _session(self, devs, modelfile, modelclass, config, resume,
                 sync_type, max_epochs=None, checkpoint=True,
                 model_parallel: int = 1, seq_parallel: int = 1,
                 pipe_parallel: int = 1, expert_parallel: int = 1,
                 monitor_dir: str | None = None,
                 **kwargs):
        if (model_parallel > 1 or seq_parallel > 1 or pipe_parallel > 1
                or expert_parallel > 1):
            from theanompi_tpu.parallel.mesh import (
                MeshSpec,
                make_training_mesh,
            )

            mesh = make_training_mesh(
                MeshSpec(data=-1, model=model_parallel, seq=seq_parallel,
                         pipe=pipe_parallel, expert=expert_parallel),
                devs)
        else:
            mesh = data_mesh(len(devs), devs)
        cls = resolve_model_class(modelfile, modelclass)
        self.model = cls(config=config, mesh=mesh, **kwargs)
        self.result = run_bsp_session(self.model, sync_type=sync_type,
                                      resume=resume, max_epochs=max_epochs,
                                      checkpoint=checkpoint,
                                      monitor_dir=monitor_dir)
