"""Asynchronous training rules: EASGD, ASGD, GOSGD.

Parity rebuild of the reference's async worker/server processes
(SURVEY.md §2.3, §2.5, §3.3 — mount empty, no file:line):

* EASGD (Zhang et al.): a server holds *center* params; each worker
  trains tau local iterations then does an elastic exchange
  (worker -= a(worker-center); center += a(worker-center)).
* ASGD: classic parameter server — workers push grads, the server
  applies its optimizer and returns fresh params.
* GOSGD (Blot et al.): no server; each worker keeps (params, weight)
  and, with probability p per iteration, halves its weight and sends
  (params, weight/2) to a uniformly-random peer, which merges by
  weighted average.

TPU-native redesign: the reference's one-MPI-rank-per-GPU topology
becomes one *worker thread per device (or device subset)* inside the
controller process, each running its own jitted step on its own
sub-mesh; server state lives on the host (parallel/server.py) and
parameter traffic is XLA host<->device transfer.  Each worker trains
on its own data shard (``shard_rank``/``shard_size``), like the
reference's per-rank shard lists.  Failure semantics stay fail-fast:
any worker exception aborts the session (SURVEY.md §5.3).
"""

from __future__ import annotations

import os
import threading
from typing import Any

import jax
import numpy as np

from theanompi_tpu.models.base import TpuModel
from theanompi_tpu.parallel.exchanger import gosgd_merge
from theanompi_tpu.parallel.mesh import data_mesh, replicate
from theanompi_tpu.parallel.server import ASGDServer, EASGDServer, GossipHub
from theanompi_tpu.rules.base import Rule, resolve_model_class
from theanompi_tpu.utils.checkpoint import Checkpointer
from theanompi_tpu.utils.recorder import Recorder

PyTree = Any


class _AsyncRule(Rule):
    """Shared scaffolding: N worker threads, one model per device."""

    def _build_workers(self, devs, modelfile, modelclass, config, **kwargs):
        cls = resolve_model_class(modelfile, modelclass)
        models = []
        for i, dev in enumerate(devs):
            m = cls(config=config, mesh=data_mesh(1, [dev]),
                    shard_rank=i, shard_size=len(devs), **kwargs)
            models.append(m)
            # share worker 0's dataset: iterators are created per epoch
            # and the source arrays/files are read-only
            kwargs.setdefault("data", m.data)
        return models

    def _run_worker_threads(self, targets):
        errors: list[BaseException] = []
        abort = threading.Event()

        def wrap(fn, rank):
            def run():
                try:
                    fn(abort)
                except BaseException as e:
                    errors.append(e)
                    abort.set()
            t = threading.Thread(target=run, daemon=True,
                                 name=f"{self.name}-worker{rank}")
            return t

        threads = [wrap(fn, i) for i, fn in enumerate(targets)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]


class EASGD(_AsyncRule):
    """Elastic-averaging SGD (reference rule #2)."""

    name = "EASGD"

    def _session(self, devs, modelfile, modelclass, config, resume,
                 sync_type, tau: int = 10, alpha: float = 0.5,
                 max_epochs: int | None = None, checkpoint: bool = True,
                 **kwargs):
        models = self._build_workers(devs, modelfile, modelclass, config,
                                     **kwargs)
        self.model = models[0]
        cfg = self.model.config

        ckpt = Checkpointer(os.path.join(cfg.snapshot_dir, self.model.name)) \
            if checkpoint else None
        start_epoch = 0
        if resume:
            if ckpt is None:
                raise ValueError("resume=True requires checkpoint=True")
            latest = ckpt.latest_epoch()
            if latest is not None:
                payload = ckpt.restore(latest, like={
                    "state": models[0].state, "epoch": 0})
                start_epoch = int(payload["epoch"]) + 1
                center0 = jax.device_get(payload["state"].params)
                for m in models:
                    m.state = m.state.replace(
                        params=replicate(center0, m.mesh))
                    m.adjust_hyperp(start_epoch)
        server = EASGDServer(models[0].state.params, alpha=alpha)
        self.server = server
        n_epochs = cfg.n_epochs if max_epochs is None else min(cfg.n_epochs,
                                                               start_epoch + max_epochs)
        recorders = [Recorder(rank=i, size=len(devs),
                              print_freq=cfg.print_freq)
                     for i in range(len(models))]
        epoch_done = threading.Semaphore(0)

        def make_worker(rank: int):
            model, recorder = models[rank], recorders[rank]

            def work(abort: threading.Event):
                model.compile_iter_fns("avg")
                it_total = 0
                for epoch in range(start_epoch, n_epochs):
                    n_iters = model.begin_epoch(epoch)
                    for it in range(n_iters):
                        if abort.is_set():
                            return
                        if it_total % tau == 0:
                            recorder.start()
                            new_params = server.exchange(model.state.params)
                            model.state = model.state.replace(
                                params=new_params)
                            recorder.end("comm")
                        model.train_iter(it, recorder)
                        it_total += 1
                    model._flush_metrics(recorder)
                    model.adjust_hyperp(epoch + 1)
                    if rank == 0:
                        epoch_done.release()
                # final elastic sync so worker state ~ center
                model.state = model.state.replace(
                    params=server.exchange(model.state.params))
                model.cleanup()

            return work

        # Server-side orchestration (validation + checkpoint per epoch of
        # worker 0 — the reference's server orchestrated validation too).
        # Owns its own model instance: worker 0's state is being mutated
        # concurrently by its thread.
        val_model = resolve_model_class(modelfile, modelclass)(
            config=config, mesh=data_mesh(1, [devs[0]]),
            **{**kwargs, "data": models[0].data})
        val_model.compile_iter_fns("avg")
        # rank 0 so the per-epoch summary prints; worker recorders are
        # never touched from this thread
        val_recorder = Recorder(rank=0, size=len(devs),
                                print_freq=cfg.print_freq)
        val_results: list[dict] = []

        def orchestrate(abort: threading.Event):
            for epoch in range(start_epoch, n_epochs):
                while not epoch_done.acquire(timeout=0.5):
                    if abort.is_set():
                        return
                center = jax.tree.map(np.asarray, server.get_center())
                val_model.state = val_model.state.replace(
                    params=replicate(center, val_model.mesh))
                val = val_model.val_epoch(val_recorder)
                val_results.append(val)
                if ckpt is not None:
                    ckpt.save(epoch, {"state": val_model.state,
                                      "epoch": epoch})
                val_recorder.epoch_summary(epoch, val.get("loss"),
                                           val.get("error"))

        self._run_worker_threads(
            [make_worker(i) for i in range(len(models))] + [orchestrate])
        if ckpt is not None:
            ckpt.close()
        self.result = {
            "val": val_results[-1] if val_results else {},
            "val_curve": val_results,
            "n_exchanges": server.n_exchanges,
            "center": server.get_center(),
        }


class ASGD(_AsyncRule):
    """Async parameter server (reference rule #3)."""

    name = "ASGD"

    def _session(self, devs, modelfile, modelclass, config, resume,
                 sync_type, max_epochs: int | None = None,
                 checkpoint: bool = True, **kwargs):
        if resume:
            raise NotImplementedError(
                "ASGD resume is not implemented yet; restart from scratch "
                "or use BSP/EASGD which support --resume")
        models = self._build_workers(devs, modelfile, modelclass, config,
                                     **kwargs)
        self.model = models[0]
        cfg = self.model.config
        server = ASGDServer(models[0].state.params, models[0].tx)
        self.server = server
        n_epochs = cfg.n_epochs if max_epochs is None else min(cfg.n_epochs,
                                                               max_epochs)
        recorders = [Recorder(rank=i, size=len(devs),
                              print_freq=cfg.print_freq)
                     for i in range(len(models))]

        def make_worker(rank: int):
            model, recorder = models[rank], recorders[rank]

            def work(abort: threading.Event):
                gstep = model.compile_grad_fn()
                for epoch in range(n_epochs):
                    n_iters = model.begin_epoch(epoch)
                    for it in range(n_iters):
                        if abort.is_set():
                            return
                        recorder.start()
                        batch = next(model._train_iter)
                        recorder.end("wait")
                        recorder.start()
                        grads, new_ms, metrics = gstep(model.state, batch,
                                                       model._next_rng())
                        recorder.end("calc", block_on=metrics)
                        recorder.start()
                        fresh = server.push_pull(grads)
                        model.state = model.state.replace(
                            params=replicate(fresh, model.mesh),
                            model_state=new_ms)
                        recorder.end("comm")
                        recorder.train_metrics(float(metrics["loss"]),
                                               float(metrics["error"]),
                                               model.global_batch)
                    new_lr = model.adjust_hyperp(epoch + 1)
                    if rank == 0:
                        # the server's optimizer applies the updates, so
                        # the schedule must reach IT (workers' own
                        # opt_states are unused under ASGD)
                        server.set_lr(new_lr)
                model.cleanup()

            return work

        self._run_worker_threads([make_worker(i) for i in range(len(models))])
        center = jax.device_get(server.get_center())
        probe = models[0]
        probe.compile_iter_fns("avg")
        probe.state = probe.state.replace(params=replicate(center, probe.mesh))
        val = probe.val_epoch(recorders[0])
        self.result = {"val": val, "n_updates": server.n_updates,
                       "center": center}


class GOSGD(_AsyncRule):
    """Decentralized gossip SGD (reference rule #4)."""

    name = "GOSGD"

    def _session(self, devs, modelfile, modelclass, config, resume,
                 sync_type, p_push: float = 0.1,
                 max_epochs: int | None = None, **kwargs):
        if resume:
            raise NotImplementedError(
                "GOSGD resume is not implemented yet; restart from scratch "
                "or use BSP/EASGD which support --resume")
        models = self._build_workers(devs, modelfile, modelclass, config,
                                     **kwargs)
        self.model = models[0]
        cfg = self.model.config
        n = len(models)
        hub = GossipHub(n)
        n_epochs = cfg.n_epochs if max_epochs is None else min(cfg.n_epochs,
                                                               max_epochs)
        recorders = [Recorder(rank=i, size=n, print_freq=cfg.print_freq)
                     for i in range(n)]
        weights = [1.0 / n] * n  # gossip weights, renormalized by merges

        def make_worker(rank: int):
            model, recorder = models[rank], recorders[rank]
            rng = np.random.default_rng(cfg.seed + 31 * rank)

            def work(abort: threading.Event):
                model.compile_iter_fns("avg")
                for epoch in range(n_epochs):
                    n_iters = model.begin_epoch(epoch)
                    for it in range(n_iters):
                        if abort.is_set():
                            return
                        # merge anything gossiped to us
                        recorder.start()
                        for recv_params, recv_w in hub.drain(rank):
                            merged, new_w = gosgd_merge(
                                model.state.params, weights[rank],
                                recv_params, recv_w)
                            model.state = model.state.replace(params=merged)
                            weights[rank] = float(new_w)
                        recorder.end("comm")
                        model.train_iter(it, recorder)
                        # push with probability p to a random peer
                        if n > 1 and rng.random() < p_push:
                            dst = int(rng.integers(0, n - 1))
                            dst = dst if dst < rank else dst + 1
                            recorder.start()
                            half = weights[rank] / 2.0
                            if hub.push(dst, model.state.params, half):
                                weights[rank] = half
                            recorder.end("comm")
                    model._flush_metrics(recorder)
                    model.adjust_hyperp(epoch + 1)
                hub.deactivate(rank)
                model.cleanup()

            return work

        self._run_worker_threads([make_worker(i) for i in range(n)])
        # merge whatever was still in flight at shutdown (conserves the
        # gossip weight), then fold the weighted consensus
        for rank in range(n):
            for recv_params, recv_w in hub.drain(rank):
                merged, new_w = gosgd_merge(
                    jax.device_get(models[rank].state.params), weights[rank],
                    recv_params, recv_w)
                models[rank].state = models[rank].state.replace(
                    params=replicate(jax.device_get(merged),
                                     models[rank].mesh))
                weights[rank] = float(new_w)
        # consensus = weight-averaged params across workers (fetched to
        # host first — each worker's params are committed to its device)
        consensus = jax.device_get(models[0].state.params)
        acc_w = weights[0]
        for i in range(1, n):
            consensus, acc_w = gosgd_merge(
                consensus, acc_w, jax.device_get(models[i].state.params),
                weights[i])
        probe = models[0]
        probe.compile_iter_fns("avg")
        probe.state = probe.state.replace(
            params=replicate(jax.device_get(consensus), probe.mesh))
        val = probe.val_epoch(recorders[0])
        self.result = {"val": val, "weights": weights,
                       "consensus": jax.tree.map(np.asarray, consensus)}
