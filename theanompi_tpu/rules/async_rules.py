"""Asynchronous training rules: EASGD, ASGD, GOSGD.

Parity rebuild of the reference's async worker/server processes
(SURVEY.md §2.3, §2.5, §3.3 — mount empty, no file:line):

* EASGD (Zhang et al.): a server holds *center* params; each worker
  trains tau local iterations then does an elastic exchange
  (worker -= a(worker-center); center += a(worker-center)).
* ASGD: classic parameter server — workers push grads, the server
  applies its optimizer and returns fresh params.
* GOSGD (Blot et al.): no server; each worker keeps (params, weight)
  and, with probability p per iteration, halves its weight and sends
  (params, weight/2) to a uniformly-random peer, which merges by
  weighted average.

TPU-native redesign: the reference's one-MPI-rank-per-GPU topology
becomes one *worker thread per device (or device subset)* inside the
controller process, each running its own jitted step on its own
sub-mesh; server state lives on the host (parallel/server.py) and
parameter traffic is XLA host<->device transfer.  Each worker trains
on its own data shard (``shard_rank``/``shard_size``), like the
reference's per-rank shard lists.  Failure semantics stay fail-fast by
DEFAULT: any worker exception aborts the session (SURVEY.md §5.3).
``max_restarts > 0`` opts into supervised recovery
(resilience.supervisor / docs/RESILIENCE.md): a crashed EASGD/ASGD
worker is restarted from the center params with a bounded budget; a
crashed GOSGD worker (no center to restart from) is deactivated via
the hub's existing path so peers stop gossiping at it; the session
aborts only when the surviving-worker quorum (``min_workers``) is
lost.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any

import jax
import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.models.base import TpuModel
from theanompi_tpu.parallel.aggregate import (
    AggregatedExchange,
    LocalAggregator,
)
from theanompi_tpu.parallel.exchanger import (
    easgd_apply_delta,
    gosgd_merge,
    gosgd_scale_momentum,
)
from theanompi_tpu.parallel.mesh import data_mesh, replicate
from theanompi_tpu.parallel.server import ASGDServer, EASGDServer, GossipHub
from theanompi_tpu.parallel.service import (
    RemoteASGD,
    RemoteEASGD,
    RemoteGossipHub,
    ServiceClient,
    ShardedServiceClient,
)
from theanompi_tpu.parallel.shards import (
    ShardedASGD,
    ShardedEASGD,
    shard_addresses,
)
from theanompi_tpu.resilience import faults
from theanompi_tpu.resilience.supervisor import WorkerSupervisor
from theanompi_tpu.rules.base import Rule, resolve_model_class
from theanompi_tpu.utils.checkpoint import Checkpointer
from theanompi_tpu.utils.helper_funcs import load_params_npz, save_params_npz
from theanompi_tpu.utils.recorder import Recorder

PyTree = Any


def _prune_gosgd_sidecars(sidecar_dir: str, kept: set[int]) -> None:
    """Drop per-worker param npz / meta json for epochs the orbax
    manager pruned (max_to_keep) — otherwise a long GOSGD run leaks a
    full parameter set per worker per epoch."""
    import glob
    import re

    for path in glob.glob(os.path.join(sidecar_dir, "gosgd_w*_*.npz")) + \
            glob.glob(os.path.join(sidecar_dir, "gosgd_meta_*.json")):
        m = re.search(r"_(\d+)\.(?:npz|json)$", path)
        if m and int(m.group(1)) not in kept:
            try:
                os.unlink(path)
            except OSError:
                pass


# _ExchangePipe moved to parallel/pipe.py (ISSUE 8: the shard router
# reuses it); re-exported here so existing importers keep working.
from theanompi_tpu.parallel.pipe import _STOP, _ExchangePipe  # noqa: F401,E402


class _AsyncRule(Rule):
    """Shared scaffolding: N worker threads, one model per device."""

    def _build_workers(self, devs, modelfile, modelclass, config, **kwargs):
        cls = resolve_model_class(modelfile, modelclass)
        cfg = config if config is not None else cls.default_config()
        if getattr(cfg, "steps_per_call", 1) > 1:
            raise ValueError(
                "steps_per_call>1 (the scanned multi-step program) is a "
                "BSP feature; the async rules exchange/gossip BETWEEN "
                "iterations, which a fused k-step program would skip")
        if getattr(cfg, "grad_accum_steps", 1) > 1:
            raise ValueError(
                "grad_accum_steps>1 is a BSP feature; the async rules' "
                "exchange cadence is per-iteration")
        if getattr(cfg, "zero_sharding", False):
            raise ValueError(
                "zero_sharding is a BSP feature; async workers own "
                "1-device meshes where a data-axis shard is the whole "
                "state (no memory win, silently misleading)")
        models = []
        for i, dev in enumerate(devs):
            m = cls(config=config, mesh=data_mesh(1, [dev]),
                    shard_rank=i, shard_size=len(devs), **kwargs)
            models.append(m)
            # share worker 0's dataset: iterators are created per epoch
            # and the source arrays/files are read-only
            kwargs.setdefault("data", m.data)
        return models

    def _run_worker_threads(self, targets, extra=(), supervisor=None):
        """Run worker targets (+ ``extra`` non-worker targets, e.g.
        EASGD's orchestrator).  ``supervisor=None`` is the reference's
        fail-fast path; a WorkerSupervisor applies bounded
        restart-from-center / lose-with-quorum semantics to the
        worker targets only."""
        if supervisor is not None:
            supervisor.run(targets, extra=extra)
            return
        errors: list[BaseException] = []
        abort = threading.Event()

        def wrap(fn, rank):
            def run():
                try:
                    fn(abort)
                except BaseException as e:
                    errors.append(e)
                    abort.set()
            t = threading.Thread(target=run, daemon=True,
                                 name=f"{self.name}-worker{rank}")
            return t

        threads = [wrap(fn, i)
                   for i, fn in enumerate(list(targets) + list(extra))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]


class EASGD(_AsyncRule):
    """Elastic-averaging SGD (reference rule #2)."""

    name = "EASGD"

    def _session(self, devs, modelfile, modelclass, config, resume,
                 sync_type, tau: int = 10, alpha: float = 0.5,
                 max_epochs: int | None = None, checkpoint: bool = True,
                 server_addr: str | None = None,
                 session_id: str | None = None,
                 overlap: bool = False,
                 local_aggregation: bool = False,
                 max_restarts: int = 0, min_workers: int = 1, **kwargs):
        models = self._build_workers(devs, modelfile, modelclass, config,
                                     **kwargs)
        self.model = models[0]
        cfg = self.model.config
        session_id = session_id or uuid.uuid4().hex

        ckpt = Checkpointer(os.path.join(cfg.snapshot_dir, self.model.name)) \
            if checkpoint else None
        start_epoch = 0
        if resume:
            if ckpt is None:
                raise ValueError("resume=True requires checkpoint=True")
            # integrity-checked: a corrupt latest checkpoint falls back
            # to the previous kept epoch (resilience.recovery)
            _, payload = ckpt.restore_latest_verified(like={
                "state": models[0].state, "epoch": 0})
            if payload is not None:
                start_epoch = int(payload["epoch"]) + 1
                center0 = jax.device_get(payload["state"].params)
                for m in models:
                    m.state = m.state.replace(
                        params=replicate(center0, m.mesh))
                    m.adjust_hyperp(start_epoch)
        # a comma-separated server_addr is a SHARD FLEET: the center is
        # leaf-range-partitioned across the listed shard services
        # (parallel/shards.py, docs/DESIGN.md "Sharded parameter
        # service"); a single address keeps the one-center client
        addrs = shard_addresses(server_addr)
        sharded = addrs is not None and len(addrs) > 1

        def connect():
            """Each worker thread gets its OWN connection (the service
            handles connections concurrently; one shared client would
            serialize every exchange on the client lock).  Workers JOIN
            the session (params=None): reading models[0].state from a
            worker thread would race with worker 0's donating train
            step, and re-shipping the tree N times is waste.  In-process
            mode all threads share the store object directly."""
            if server_addr:
                # DCN path: the center lives in a separate service
                # process (possibly another machine) — parallel/service
                if sharded:
                    return ShardedEASGD(addrs, None, alpha=alpha,
                                        session_id=session_id)
                return RemoteEASGD(addrs[0], None, alpha=alpha,
                                   session_id=session_id)
            return server

        if server_addr:
            # session creator: ship the initial center from the MAIN
            # thread, before any worker's train step can donate it
            init_params = jax.device_get(models[0].state.params)
            server = (ShardedEASGD(addrs, init_params, alpha=alpha,
                                   session_id=session_id)
                      if sharded else
                      RemoteEASGD(addrs[0], init_params,
                                  alpha=alpha, session_id=session_id))
        else:
            server = EASGDServer(models[0].state.params, alpha=alpha)
        self.server = server
        # hierarchical aggregation (parallel/aggregate.py): ONE wire
        # exchange per shard per period for all local workers — the
        # period mean rides the tagged aggregate op, the pre-update
        # center fans back over shared memory.  Registered up front so
        # the first period already aggregates at full fan-in; workers
        # fall back to direct exchange whenever the plane is down.
        agg = None
        if local_aggregation:
            if len(models) * alpha > 1.0 + 1e-9:
                raise ValueError(
                    f"local_aggregation composes the period's elastic "
                    f"moves against ONE center version, so the center "
                    f"coefficient is n*alpha = {len(models)}*{alpha} "
                    f"= {len(models) * alpha:g} > 1 — the center "
                    "overshoots the worker mean every period and "
                    "oscillates/diverges.  Lower --alpha to <= "
                    f"1/{len(models)} (the EASGD paper's beta = "
                    "N*alpha parameterization; docs/DESIGN.md "
                    "'Hierarchical exchange')")
            agg = LocalAggregator("easgd", server, alpha=alpha)
            for i in range(len(models)):
                agg.register(i)
        self.aggregator = agg
        n_epochs = cfg.n_epochs if max_epochs is None else min(cfg.n_epochs,
                                                               start_epoch + max_epochs)
        # supervised recovery (opt-in): a dead worker restarts from the
        # CENTER params — the whole point of keeping an elastic center
        sup = None
        if max_restarts > 0:
            def _restart_from_center(rank: int) -> None:
                center = jax.tree.map(np.asarray, server.get_center())
                models[rank].state = models[rank].state.replace(
                    params=replicate(center, models[rank].mesh))

            sup = WorkerSupervisor(
                n_workers=len(models), max_restarts=max_restarts,
                min_workers=min_workers,
                restart_from=_restart_from_center, name=self.name)
        recorders = [Recorder(rank=i, size=len(devs),
                              print_freq=cfg.print_freq,
                              flops_per_sample=models[
                                  i].train_flops_per_sample,
                              images_are_global=False)
                     for i in range(len(models))]
        epoch_done = threading.Semaphore(0)

        def make_worker(rank: int):
            model, recorder = models[rank], recorders[rank]
            # outlives one work() invocation: a supervised restart
            # resumes at the epoch the worker died in — re-running
            # completed epochs would retrain redundantly, and a
            # restarted rank 0 would re-release epoch_done for epochs
            # the orchestrator already validated
            progress = {"epoch": start_epoch}

            def work(abort: threading.Event):
                # aggregated mode: the port submits to the host's
                # LocalAggregator instead of owning a ServiceClient —
                # the rule's direct `connect` stays its lazy fallback
                srv = (AggregatedExchange(agg, rank, connect)
                       if agg is not None else connect())
                # overlap mode: this worker's exchange thread — RPCs
                # run there while the worker computes the next tau
                # iterations; bounded staleness 1 (docs/DESIGN.md
                # "Overlapped exchange")
                # the fetch-to-host of the result ALSO happens in the
                # exchange thread (an in-process store returns device
                # arrays committed to the server's jit device; fetching
                # them at collect() would re-serialize the worker on
                # exactly the latency overlap exists to hide)
                pipe = _ExchangePipe(
                    lambda p: jax.tree.map(
                        np.asarray, jax.device_get(srv.exchange(p))),
                    "easgd/exchange", rank) if overlap else None

                def collect_and_correct():
                    """Apply the finished exchange's elastic force to
                    the params the worker has NOW (easgd_apply_delta:
                    same force, one period late)."""
                    with monitor.span("easgd/exchange_collect",
                                      worker=str(rank)):
                        snap, returned = pipe.collect()
                    model.state = model.state.replace(
                        params=easgd_apply_delta(model.state.params,
                                                 snap, returned))

                try:
                    model.compile_iter_fns("avg")
                    it_total = 0
                    for epoch in range(progress["epoch"], n_epochs):
                        progress["epoch"] = epoch
                        n_iters = model.begin_epoch(epoch)
                        for it in range(n_iters):
                            if abort.is_set():
                                return
                            faults.fire("worker_step", rule="easgd",
                                        worker=rank, step=it_total)
                            t_it = time.monotonic()
                            if it_total % tau == 0:
                                recorder.start()
                                if pipe is None:
                                    with monitor.span("easgd/exchange",
                                                      worker=str(rank)):
                                        new_params = srv.exchange(
                                            model.state.params)
                                    model.state = model.state.replace(
                                        params=new_params)
                                else:
                                    if pipe.busy():
                                        collect_and_correct()
                                    # host snapshot BEFORE the next
                                    # train dispatch can donate these
                                    # buffers; the RPC overlaps the
                                    # next tau iterations
                                    pipe.submit(jax.tree.map(
                                        np.asarray, jax.device_get(
                                            model.state.params)))
                                recorder.end("comm")
                            with monitor.span("easgd/compute",
                                              worker=str(rank)):
                                model.train_iter(it, recorder)
                            it_total += 1
                            # feeds the step histogram, heartbeat, and
                            # the cross-worker straggler detector —
                            # whose flag the supervisor consumes
                            flagged = monitor.observe_step(
                                time.monotonic() - t_it, phase="train",
                                step=it_total, worker=rank)
                            if sup is not None:
                                sup.note_straggler(rank, flagged)
                        model._flush_metrics(recorder)
                        model.adjust_hyperp(epoch + 1)
                        if rank == 0:
                            epoch_done.release()
                    if pipe is not None and pipe.busy():
                        collect_and_correct()  # drain the last one
                    # final elastic sync so worker state ~ center
                    model.state = model.state.replace(
                        params=srv.exchange(model.state.params))
                finally:
                    if pipe is not None:
                        pipe.close()
                    model.cleanup()
                    if isinstance(srv, AggregatedExchange):
                        # leaves the period quorum (a supervised
                        # restart re-registers) + closes only the
                        # port's own fallback client
                        srv.close()
                    elif srv is not server and isinstance(
                            srv, (ServiceClient, ShardedServiceClient)):
                        srv.close()

            return work

        # Server-side orchestration (validation + checkpoint per epoch of
        # worker 0 — the reference's server orchestrated validation too).
        # Owns its own model instance: worker 0's state is being mutated
        # concurrently by its thread.
        val_model = resolve_model_class(modelfile, modelclass)(
            config=config, mesh=data_mesh(1, [devs[0]]),
            **{**kwargs, "data": models[0].data})
        val_model.compile_iter_fns("avg")
        # rank 0 so the per-epoch summary prints; worker recorders are
        # never touched from this thread
        val_recorder = Recorder(rank=0, size=len(devs),
                                print_freq=cfg.print_freq,
                                flops_per_sample=self.model
                                .train_flops_per_sample,
                                images_are_global=False)
        val_results: list[dict] = []

        def orchestrate(abort: threading.Event):
            for epoch in range(start_epoch, n_epochs):
                while not epoch_done.acquire(timeout=0.5):
                    if abort.is_set():
                        return
                    if sup is not None and sup.is_lost(0):
                        # worker 0 drives this cadence; with it lost
                        # (restarts exhausted, quorum held) there will
                        # be no more epoch_done releases — stop
                        # validating instead of spinning forever
                        return
                center = jax.tree.map(np.asarray, server.get_center())
                val_model.state = val_model.state.replace(
                    params=replicate(center, val_model.mesh))
                val = val_model.val_epoch(val_recorder)
                val_results.append(val)
                if ckpt is not None:
                    ckpt.save(epoch, {"state": val_model.state,
                                      "epoch": epoch})
                val_recorder.epoch_summary(epoch, val.get("loss"),
                                           val.get("error"))

        try:
            self._run_worker_threads(
                [make_worker(i) for i in range(len(models))],
                extra=[orchestrate], supervisor=sup)
            self.result = {
                "val": val_results[-1] if val_results else {},
                "val_curve": val_results,
                "n_exchanges": server.n_exchanges,
                "center": server.get_center(),
            }
            if sup is not None:
                self.result["restarts"] = sup.restart_counts()
                self.result["lost_workers"] = sup.lost_workers()
        finally:
            if ckpt is not None:
                ckpt.close()
            if isinstance(server, (ServiceClient, ShardedServiceClient)):
                server.close()


class ASGD(_AsyncRule):
    """Async parameter server (reference rule #3)."""

    name = "ASGD"

    def _session(self, devs, modelfile, modelclass, config, resume,
                 sync_type, max_epochs: int | None = None,
                 checkpoint: bool = True, server_addr: str | None = None,
                 session_id: str | None = None,
                 overlap: bool = False,
                 local_aggregation: bool = False,
                 max_restarts: int = 0, min_workers: int = 1, **kwargs):
        models = self._build_workers(devs, modelfile, modelclass, config,
                                     **kwargs)
        self.model = models[0]
        cfg = self.model.config
        session_id = session_id or uuid.uuid4().hex

        # checkpoint/resume: the SERVER's center+opt_state are the
        # training state under ASGD (workers' own opt_states are
        # unused); stored in the canonical cross-rule payload shape
        ckpt = Checkpointer(os.path.join(cfg.snapshot_dir, self.model.name)) \
            if checkpoint else None
        start_epoch = 0
        restored_opt = None
        if resume:
            if ckpt is None:
                raise ValueError("resume=True requires checkpoint=True")
            _, payload = ckpt.restore_latest_verified(like={
                "state": models[0].state, "epoch": 0})
            if payload is not None:
                start_epoch = int(payload["epoch"]) + 1
                center0 = jax.device_get(payload["state"].params)
                restored_opt = jax.device_get(payload["state"].opt_state)
                for m in models:
                    m.state = m.state.replace(
                        params=replicate(center0, m.mesh))
                    m.adjust_hyperp(start_epoch)

        # shard-fleet server_addr (see EASGD._session): the center AND
        # its per-range optimizer states live across the listed shards
        addrs = shard_addresses(server_addr)
        sharded = addrs is not None and len(addrs) > 1
        if sharded and restored_opt is not None:
            # per-shard optax states do not reassemble/scatter (each
            # shard holds its own hyperparam/count leaves): resume
            # re-seeds the center EXACTLY and restarts server momentum
            # fresh — the same documented trade the service-restart
            # rejoin makes (docs/RESILIENCE.md)
            print("[asgd] sharded resume: center restored exactly; "
                  "server optimizer momentum restarts fresh "
                  "(docs/RESILIENCE.md)", flush=True)
            restored_opt = None

        def connect():
            """Own connection per worker thread; workers join without a
            payload (see EASGD.connect on the donation race + waste)."""
            if server_addr:
                if sharded:
                    return ShardedASGD(addrs, None,
                                       models[0].optimizer_hyperparams(),
                                       session_id=session_id)
                return RemoteASGD(addrs[0], None,
                                  models[0].optimizer_hyperparams(),
                                  session_id=session_id)
            return server

        if server_addr:
            init_params = jax.device_get(models[0].state.params)
            opt_cfg = models[0].optimizer_hyperparams()
            server = (ShardedASGD(addrs, init_params, opt_cfg,
                                  session_id=session_id)
                      if sharded else
                      RemoteASGD(addrs[0], init_params, opt_cfg,
                                 opt_state=restored_opt,
                                 session_id=session_id))
        else:
            server = ASGDServer(jax.device_get(models[0].state.params),
                                models[0].tx)
            if restored_opt is not None:
                server.set_opt_state(restored_opt)
        self.server = server
        # hierarchical aggregation (parallel/aggregate.py): the local
        # workers' gradient pushes delta-sum into ONE wire push per
        # shard per period; the fresh center fans back over shared
        # memory.  See the EASGD wiring note above.
        agg = None
        if local_aggregation:
            agg = LocalAggregator("asgd", server)
            for i in range(len(models)):
                agg.register(i)
        self.aggregator = agg
        if resume and start_epoch:
            # the restored opt_state carries the old LR; apply the
            # fast-forwarded schedule to the server (LR lives there)
            server.set_lr(models[0].adjust_hyperp(start_epoch))
        n_epochs = cfg.n_epochs if max_epochs is None else min(
            cfg.n_epochs, start_epoch + max_epochs)
        sup = None
        if max_restarts > 0:
            def _restart_from_center(rank: int) -> None:
                center = jax.tree.map(np.asarray, server.get_center())
                models[rank].state = models[rank].state.replace(
                    params=replicate(center, models[rank].mesh))

            sup = WorkerSupervisor(
                n_workers=len(models), max_restarts=max_restarts,
                min_workers=min_workers,
                restart_from=_restart_from_center, name=self.name)
        recorders = [Recorder(rank=i, size=len(devs),
                              print_freq=cfg.print_freq,
                              flops_per_sample=models[
                                  i].train_flops_per_sample,
                              images_are_global=False)
                     for i in range(len(models))]

        def make_worker(rank: int):
            model, recorder = models[rank], recorders[rank]
            # supervised restarts resume at the crash epoch: re-running
            # from start_epoch would retrain redundantly AND (rank 0)
            # re-push the EARLY-schedule LR to the server via set_lr,
            # snapping the surviving workers' global LR backwards
            progress = {"epoch": start_epoch}

            def work(abort: threading.Event):
                # aggregated mode: see the EASGD worker wiring note
                srv = (AggregatedExchange(agg, rank, connect)
                       if agg is not None else connect())
                # overlap mode: the push_pull RPC for iteration i runs
                # in the exchange thread while this worker computes
                # iteration i+1's gradients on its current (one-push-
                # stale) params — classic async-SGD pipelining with the
                # staleness bounded at 1 by the pipe's barrier
                pipe = _ExchangePipe(
                    lambda g: jax.tree.map(
                        np.asarray, jax.device_get(srv.push_pull(g))),
                    "asgd/push_pull", rank) if overlap else None
                try:
                    gstep = model.compile_grad_fn()
                    it_total = 0
                    for epoch in range(progress["epoch"], n_epochs):
                        progress["epoch"] = epoch
                        n_iters = model.begin_epoch(epoch)
                        for it in range(n_iters):
                            if abort.is_set():
                                return
                            faults.fire("worker_step", rule="asgd",
                                        worker=rank, step=it_total)
                            it_total += 1
                            t_it = time.monotonic()
                            recorder.start()
                            batch = next(model._train_iter)
                            recorder.end("wait")
                            recorder.start()
                            with monitor.span("asgd/compute",
                                              worker=str(rank)):
                                grads, new_ms, metrics = gstep(
                                    model.state, batch, model._next_rng())
                            recorder.end("calc", block_on=metrics)
                            recorder.start()
                            if pipe is None:
                                with monitor.span("asgd/push_pull",
                                                  worker=str(rank)):
                                    fresh = srv.push_pull(grads)
                                model.state = model.state.replace(
                                    params=replicate(fresh, model.mesh),
                                    model_state=new_ms)
                            else:
                                # collect the PREVIOUS push's fresh
                                # center (it overlapped this step's
                                # compute), then hand off this step's
                                # grads
                                new_params = model.state.params
                                if pipe.busy():
                                    with monitor.span(
                                            "asgd/push_pull_collect",
                                            worker=str(rank)):
                                        _, fresh = pipe.collect()
                                    new_params = replicate(fresh,
                                                           model.mesh)
                                pipe.submit(jax.tree.map(
                                    np.asarray, jax.device_get(grads)))
                                model.state = model.state.replace(
                                    params=new_params,
                                    model_state=new_ms)
                            recorder.end("comm")
                            recorder.train_metrics(float(metrics["loss"]),
                                                   float(metrics["error"]),
                                                   model.global_batch)
                            flagged = monitor.observe_step(
                                time.monotonic() - t_it, phase="train",
                                step=it, worker=rank)
                            if sup is not None:
                                sup.note_straggler(rank, flagged)
                        new_lr = model.adjust_hyperp(epoch + 1)
                        if rank == 0:
                            # the server's optimizer applies the updates,
                            # so the schedule must reach IT (workers' own
                            # opt_states are unused under ASGD).  Rank 0
                            # forwards it when ITS epoch ends — other
                            # workers may be mid-epoch, so a decay can
                            # apply to their remaining pushes up to one
                            # epoch early.  Deliberate: async pushes have
                            # no global epoch anyway, the skew is bounded
                            # by one epoch, and a step schedule is
                            # insensitive to it (tested:
                            # test_asgd_lr_schedule_reaches_server).
                            srv.set_lr(new_lr)
                            if ckpt is not None:
                                # a restarted rank 0 re-reaching an
                                # epoch it saved pre-crash: orbax
                                # silently skips the duplicate save
                                # (the pre-crash checkpoint of that
                                # epoch stands; force=True would
                                # REFUSE, not overwrite, on orbax 0.7)
                                # sharded servers have no single-tree
                                # opt_state (ShardedASGD docstring):
                                # keep the worker's own structure so
                                # the checkpoint stays restorable —
                                # resume re-seeds momentum fresh
                                opt = (jax.device_get(
                                           srv.get_opt_state())
                                       if getattr(srv,
                                                  "supports_opt_state",
                                                  True)
                                       else jax.device_get(
                                           model.state.opt_state))
                                ckpt.save(epoch, {
                                    "state": model.state.replace(
                                        params=jax.device_get(
                                            srv.get_center()),
                                        opt_state=opt,
                                    ),
                                    "epoch": epoch,
                                })
                    if pipe is not None and pipe.busy():
                        # drain: the last grads must reach the center
                        # before the session's final validation
                        _, fresh = pipe.collect()
                        model.state = model.state.replace(
                            params=replicate(fresh, model.mesh))
                finally:
                    if pipe is not None:
                        pipe.close()
                    model.cleanup()
                    if isinstance(srv, AggregatedExchange):
                        srv.close()
                    elif srv is not server and isinstance(
                            srv, (ServiceClient, ShardedServiceClient)):
                        srv.close()

            return work

        try:
            self._run_worker_threads(
                [make_worker(i) for i in range(len(models))],
                supervisor=sup)
            center = jax.device_get(server.get_center())
            n_updates = server.n_updates
        finally:
            if ckpt is not None:
                ckpt.close()
            if isinstance(server, (ServiceClient, ShardedServiceClient)):
                server.close()
        probe = models[0]
        probe.compile_iter_fns("avg")
        probe.state = probe.state.replace(params=replicate(center, probe.mesh))
        val = probe.val_epoch(recorders[0])
        self.result = {"val": val, "n_updates": n_updates,
                       "center": center}
        if sup is not None:
            self.result["restarts"] = sup.restart_counts()
            self.result["lost_workers"] = sup.lost_workers()


class GOSGD(_AsyncRule):
    """Decentralized gossip SGD (reference rule #4)."""

    name = "GOSGD"

    def _session(self, devs, modelfile, modelclass, config, resume,
                 sync_type, p_push: float = 0.1,
                 max_epochs: int | None = None,
                 checkpoint: bool = True,
                 server_addr: str | None = None,
                 n_total_workers: int | None = None,
                 rank_offset: int = 0,
                 session_id: str | None = None,
                 merge_momentum: str = "scale",
                 local_aggregation: bool = False,
                 max_restarts: int = 0, min_workers: int = 1, **kwargs):
        if merge_momentum not in ("scale", "keep"):
            raise ValueError(f"merge_momentum must be 'scale' or 'keep', "
                             f"got {merge_momentum!r}")
        if local_aggregation:
            raise ValueError(
                "GOSGD refuses hierarchical aggregation: a gossip push "
                "ships one worker's WHOLE (params, weight) to one "
                "random peer — there is no per-period center op to "
                "delta-sum or compose, so an intra-host aggregate has "
                "nothing exact to send (parallel/aggregate.py applies "
                "to the EASGD/ASGD center, docs/DESIGN.md "
                "'Hierarchical exchange')")
        addrs = shard_addresses(server_addr)
        if addrs is not None and len(addrs) > 1:
            raise ValueError(
                "GOSGD's gossip hub is unsharded — it rendezvouses WHOLE "
                "param trees, not an accumulating center, so there is "
                "nothing to leaf-range-partition; pass a single "
                "--server-addr (sharding applies to the EASGD/ASGD "
                "center, docs/DESIGN.md 'Sharded parameter service')")
        models = self._build_workers(devs, modelfile, modelclass, config,
                                     **kwargs)
        self.model = models[0]
        cfg = self.model.config
        n = len(models)
        session_id = session_id or uuid.uuid4().hex
        # DCN path: several hosts share one gossip hub in a service
        # process; this host's local workers occupy global ranks
        # [rank_offset, rank_offset + n) of n_total_workers
        n_total = n_total_workers if n_total_workers is not None else n

        def connect():
            """Own connection per worker thread (see EASGD.connect)."""
            if server_addr:
                return RemoteGossipHub(addrs[0], n_total,
                                       rank_offset=rank_offset,
                                       session_id=session_id)
            return hub

        if server_addr:
            hub = connect()
        else:
            if n_total != n or rank_offset:
                raise ValueError("n_total_workers/rank_offset need "
                                 "server_addr (the shared gossip hub)")
            hub = GossipHub(n)
        recorders = [Recorder(rank=i, size=n, print_freq=cfg.print_freq,
                              flops_per_sample=models[
                                  i].train_flops_per_sample,
                              images_are_global=False)
                     for i in range(n)]
        # gossip weights (global invariant: sum over ALL workers == 1)
        weights = [1.0 / n_total] * n

        # -- checkpoint/resume (VERDICT r1 #5): canonical cross-rule
        # payload holds worker 0's params (a legitimate model state);
        # per-worker params + gossip weights ride sidecar npz/json so a
        # GOSGD resume restores every worker exactly.  A checkpoint
        # from another rule (no sidecars) still resumes: all workers
        # start from its params with equal weights.
        ckpt = Checkpointer(os.path.join(cfg.snapshot_dir, self.model.name)) \
            if checkpoint else None
        sidecar_dir = os.path.join(cfg.snapshot_dir, self.model.name)
        start_epoch = 0
        if resume:
            if ckpt is None:
                raise ValueError("resume=True requires checkpoint=True")
            latest, payload = ckpt.restore_latest_verified(like={
                "state": models[0].state, "epoch": 0})
            if payload is not None:
                start_epoch = int(payload["epoch"]) + 1
                meta_path = os.path.join(sidecar_dir,
                                         f"gosgd_meta_{latest}.json")
                worker_paths = [os.path.join(sidecar_dir,
                                             f"gosgd_w{i}_{latest}.npz")
                                for i in range(n)]
                meta = None
                if os.path.exists(meta_path):
                    with open(meta_path) as f:
                        meta = json.load(f)
                if (meta is not None
                        and meta.get("n_workers") == n
                        and all(os.path.exists(p) for p in worker_paths)):
                    # the snapshot was taken mid-session, when some
                    # gossip weight was in flight in peers' inboxes —
                    # renormalize to this host's share so the global
                    # sum-of-weights == 1 invariant is re-established
                    restored = [float(w) for w in meta["weights"]]
                    share = n / n_total
                    s = sum(restored)
                    weights[:] = [w / s * share for w in restored]
                    for m, p in zip(models, worker_paths):
                        like = jax.tree.map(np.asarray, m.state.params)
                        m.state = m.state.replace(params=replicate(
                            load_params_npz(p, like), m.mesh))
                else:  # cross-rule ckpt or worker-count change:
                    # consensus start at equal weights
                    center0 = jax.device_get(payload["state"].params)
                    for m in models:
                        m.state = m.state.replace(
                            params=replicate(center0, m.mesh))
                for m in models:
                    m.adjust_hyperp(start_epoch)
        n_epochs = cfg.n_epochs if max_epochs is None else min(
            cfg.n_epochs, start_epoch + max_epochs)
        # GOSGD supervision: there is NO center to restart a dead
        # worker from — a failed worker falls back to the hub's
        # existing deactivate path (peers stop pushing to the corpse,
        # conserving gossip weight); the session aborts only when the
        # quorum is lost (docs/RESILIENCE.md)
        sup = None
        if max_restarts > 0:
            sup = WorkerSupervisor(
                n_workers=n, max_restarts=0, min_workers=min_workers,
                restart_from=None,
                on_lost=lambda rank: hub.deactivate(rank),
                name=self.name)

        def make_worker(rank: int):
            model, recorder = models[rank], recorders[rank]
            rng = np.random.default_rng(cfg.seed + 31 * (rank + rank_offset))
            g_rank = rank + rank_offset

            def work(abort: threading.Event):
                h = connect()
                try:
                    gosgd_loop(h, abort)
                finally:
                    model.cleanup()
                    if h is not hub and isinstance(h, ServiceClient):
                        h.close()

            def gosgd_loop(h, abort):
                model.compile_iter_fns("avg")
                it_total = 0
                for epoch in range(start_epoch, n_epochs):
                    n_iters = model.begin_epoch(epoch)
                    for it in range(n_iters):
                        if abort.is_set():
                            return
                        faults.fire("worker_step", rule="gosgd",
                                    worker=g_rank, step=it_total)
                        it_total += 1
                        t_it = time.monotonic()
                        # merge anything gossiped to us
                        recorder.start()
                        for recv_params, recv_w in h.drain(rank):
                            own_w = weights[rank]
                            merged, new_w = gosgd_merge(
                                model.state.params, own_w,
                                recv_params, recv_w)
                            if merge_momentum == "scale" and new_w > 0:
                                # momentum rides the same weighted
                                # average (sender's taken as 0) — the
                                # measured stale-momentum divergence
                                # fix, see gosgd_scale_momentum
                                opt = gosgd_scale_momentum(
                                    model.state.opt_state,
                                    own_w / new_w)
                                model.state = model.state.replace(
                                    params=merged, opt_state=opt)
                            else:
                                model.state = model.state.replace(
                                    params=merged)
                            weights[rank] = float(new_w)
                        recorder.end("comm")
                        model.train_iter(it, recorder)
                        # push with probability p to a random peer
                        # (global rank space when hosts share a hub)
                        if n_total > 1 and rng.random() < p_push:
                            dst = int(rng.integers(0, n_total - 1))
                            dst = dst if dst < g_rank else dst + 1
                            recorder.start()
                            half = weights[rank] / 2.0
                            with monitor.span("gosgd/push",
                                              worker=str(rank)):
                                if h.push(dst, model.state.params, half):
                                    weights[rank] = half
                            recorder.end("comm")
                        flagged = monitor.observe_step(
                            time.monotonic() - t_it, phase="train",
                            step=it, worker=rank)
                        if sup is not None:
                            sup.note_straggler(rank, flagged)
                    model._flush_metrics(recorder)
                    model.adjust_hyperp(epoch + 1)
                    if ckpt is not None:
                        # each worker snapshots its OWN params from its
                        # own thread — another thread's state may be
                        # donated by its in-flight train step at any
                        # moment (cross-worker reads race with XLA
                        # buffer donation); slight cross-worker epoch
                        # skew is inherent to the async rule
                        own = jax.device_get(model.state.params)
                        save_params_npz(os.path.join(
                            sidecar_dir, f"gosgd_w{rank}_{epoch}.npz"), own)
                        if rank == 0:
                            ckpt.save(epoch, {
                                "state": model.state.replace(params=own),
                                "epoch": epoch,
                            })
                            with open(os.path.join(
                                    sidecar_dir,
                                    f"gosgd_meta_{epoch}.json"), "w") as f:
                                json.dump({"epoch": epoch, "n_workers": n,
                                           "weights": list(weights)}, f)
                            _prune_gosgd_sidecars(sidecar_dir,
                                                  ckpt.kept_epochs())
                h.deactivate(rank)

            return work

        try:
            self._run_worker_threads([make_worker(i) for i in range(n)],
                                     supervisor=sup)
            # merge whatever was still in flight at shutdown (conserves
            # the gossip weight), then fold the weighted consensus
            for rank in range(n):
                for recv_params, recv_w in hub.drain(rank):
                    merged, new_w = gosgd_merge(
                        jax.device_get(models[rank].state.params),
                        weights[rank], recv_params, recv_w)
                    models[rank].state = models[rank].state.replace(
                        params=replicate(jax.device_get(merged),
                                         models[rank].mesh))
                    weights[rank] = float(new_w)
            # consensus = weight-averaged params across workers (fetched
            # to host first — each worker's params are committed to its
            # device)
            consensus = jax.device_get(models[0].state.params)
            acc_w = weights[0]
            for i in range(1, n):
                consensus, acc_w = gosgd_merge(
                    consensus, acc_w,
                    jax.device_get(models[i].state.params), weights[i])
        finally:
            if ckpt is not None:
                ckpt.close()
            if isinstance(hub, ServiceClient):
                hub.close()
        probe = models[0]
        probe.compile_iter_fns("avg")
        probe.state = probe.state.replace(
            params=replicate(jax.device_get(consensus), probe.mesh))
        val = probe.val_epoch(recorders[0])
        self.result = {"val": val, "weights": weights,
                       "consensus": jax.tree.map(np.asarray, consensus)}
        if sup is not None:
            self.result["lost_workers"] = sup.lost_workers()
