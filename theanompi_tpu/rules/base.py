"""Rule API — the user-facing training-rule objects.

Parity surface of the reference's rule classes in
``theanompi/__init__.py`` (SURVEY.md §2.2 — mount empty, no file:line):

    rule = BSP()
    rule.init(devices=..., modelfile='...', modelclass='...')
    rule.wait()

TPU-native inversion: the reference's ``init`` synthesized an
``mpirun`` command and spawned N OS processes (one per GPU).  Here a
rule builds a device mesh inside THIS process and runs its training
session on a background thread — ``wait()`` joins it and re-raises any
failure (fail-fast, matching the reference's a-dead-rank-kills-the-job
behavior, SURVEY.md §5.3).  Multi-host launch (one process per host,
``jax.distributed``) is the launcher's job, not the rule's.
"""

from __future__ import annotations

import importlib
import threading
import traceback
from typing import Any, Sequence

import jax

from theanompi_tpu.models.base import ModelConfig, TpuModel
from theanompi_tpu.parallel.mesh import data_mesh


def resolve_model_class(modelfile: str, modelclass: str) -> type:
    """Import ``modelclass`` from module path ``modelfile`` (the
    reference's modelfile/modelclass convention, SURVEY.md §2.1)."""
    try:
        mod = importlib.import_module(modelfile)
    except ModuleNotFoundError as e:
        from theanompi_tpu.models import MODEL_ZOO

        raise ModuleNotFoundError(
            f"model module {modelfile!r} not found; available zoo models: "
            f"{', '.join(sorted(MODEL_ZOO))}"
        ) from e
    try:
        return getattr(mod, modelclass)
    except AttributeError as e:
        raise AttributeError(
            f"module {modelfile!r} has no class {modelclass!r}"
        ) from e


def resolve_devices(devices: int | Sequence | None,
                    global_mesh: bool = False) -> list:
    """Accept None (all), an int count, device indices, or jax Devices.

    Single-process: local devices.  Multi-host (``jax.distributed``
    initialized, ``process_count() > 1``) with ``global_mesh=True``
    (BSP — one SPMD program): the GLOBAL device list, so every host
    traces the same program over one mesh and ``psum`` crosses DCN;
    device subsetting is not supported there (each host participates
    with all its chips).  Rules that place per-worker state
    (``global_mesh=False``, the async rules) must only ever see devices
    this process addresses.
    """
    if jax.process_count() > 1:
        if not global_mesh:
            raise NotImplementedError(
                "async rules under multi-host launch need the DCN server "
                "transport (parallel/service); run them per-host, or use "
                "BSP for multi-host")
        if devices is not None:
            raise ValueError(
                "device selection is not supported under multi-host launch; "
                "all devices of all hosts form the mesh (got "
                f"devices={devices!r})")
        return list(jax.devices())
    all_devs = jax.local_devices()
    if devices is None:
        return list(all_devs)
    if isinstance(devices, int):
        if devices > len(all_devs):
            raise ValueError(
                f"requested {devices} devices, have {len(all_devs)}"
            )
        return list(all_devs)[:devices]
    out = []
    for d in devices:
        if isinstance(d, int):
            out.append(all_devs[d])
        elif isinstance(d, str):
            # reference-style 'cuda0' strings: keep the index, ignore the
            # prefix — devices are whatever the platform provides
            idx = int("".join(ch for ch in d if ch.isdigit()) or 0)
            out.append(all_devs[idx])
        else:
            out.append(d)
    return out


class Rule:
    """Base: owns session thread + error propagation."""

    name = "rule"
    #: True for rules that run one SPMD program over every device of
    #: every host (BSP); False for rules that place per-worker state on
    #: individual local devices (the async rules).
    uses_global_mesh = False

    def __init__(self):
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        self.model: TpuModel | None = None
        self.result: dict[str, Any] = {}

    def init(self, devices=None, modelfile: str = "theanompi_tpu.models.cifar10",
             modelclass: str = "Cifar10_model",
             config: ModelConfig | None = None,
             resume: bool = False, sync_type: str = "avg",
             **kwargs) -> "Rule":
        devs = resolve_devices(devices, global_mesh=self.uses_global_mesh)
        self._start(devs, modelfile, modelclass, config, resume, sync_type,
                    **kwargs)
        return self

    def wait(self) -> dict[str, Any]:
        if self._thread is None:
            raise RuntimeError("call init() before wait()")
        self._thread.join()
        if self._error is not None:
            raise self._error
        return self.result

    # -- internals --

    def _start(self, devs, modelfile, modelclass, config, resume, sync_type,
               **kwargs):
        def run():
            try:
                # telemetry for the whole session (no-op unless
                # $THEANOMPI_TPU_MONITOR or a nested session enables
                # it); an escaping exception triggers the postmortem
                # dump before landing in self._error.  rank = host
                # index so multi-host runs on a shared filesystem get
                # distinct heartbeat/snapshot files.
                from theanompi_tpu import monitor

                with monitor.session(rank=jax.process_index()):
                    try:
                        self._session(devs, modelfile, modelclass, config,
                                      resume, sync_type, **kwargs)
                    except BaseException as e:
                        try:
                            # resilience postmortem hook: a machine-
                            # readable crash marker + resume hint
                            # beside the monitor's postmortem dump
                            # (no-op when monitoring is off); must run
                            # INSIDE the session while telemetry is
                            # still live
                            from theanompi_tpu.resilience import recovery

                            recovery.record_crash(self.name, e,
                                                  model=self.model)
                        except Exception:
                            pass
                        raise
            except BaseException as e:  # propagated by wait()
                traceback.print_exc()
                self._error = e

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"{self.name}-session")
        self._thread.start()

    def _session(self, devs, modelfile, modelclass, config, resume,
                 sync_type, **kwargs):
        raise NotImplementedError
