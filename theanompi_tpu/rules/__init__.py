from theanompi_tpu.rules.base import Rule, resolve_devices, resolve_model_class
from theanompi_tpu.rules.bsp import BSP, run_bsp_session

__all__ = ["Rule", "BSP", "EASGD", "ASGD", "GOSGD",
           "run_bsp_session", "resolve_devices", "resolve_model_class"]


def __getattr__(name):
    # Async rules import lazily (they pull in the server/actor stack).
    if name in ("EASGD", "ASGD", "GOSGD"):
        from theanompi_tpu.rules import async_rules

        return getattr(async_rules, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
