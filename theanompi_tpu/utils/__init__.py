from theanompi_tpu.utils.recorder import Recorder
from theanompi_tpu.utils.helper_funcs import (
    divide_batches,
    get_learning_rate,
    load_params_npz,
    save_params_npz,
    scale_lr,
    set_learning_rate,
    tree_size,
    tree_to_vector,
    vector_to_tree,
)

__all__ = [
    "Recorder", "divide_batches", "scale_lr", "set_learning_rate",
    "get_learning_rate", "tree_to_vector", "vector_to_tree", "tree_size",
    "save_params_npz", "load_params_npz",
]
