"""Small shared helpers.

Parity counterpart of the reference's ``theanompi/lib/helper_funcs.py``
(SURVEY.md §2.7 — mount empty, no file:line).  The reference's helpers
were MPI-buffer plumbing (``bufint``, ``dtype_to_mpi``) plus batch
division, learning-rate scaling and npz param save/load.  The MPI
plumbing has no TPU analogue (XLA owns the buffers); what survives is
the arithmetic and the npz format.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

PyTree = Any


#: env channel for the persistent XLA compilation cache — set by the
#: launchers'/tools' ``--compilation-cache-dir`` flags so every
#: subprocess a run spawns shares one cache
COMPILATION_CACHE_ENV = "THEANOMPI_TPU_COMPILATION_CACHE"


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Turn on JAX's persistent compilation cache under ``cache_dir``
    (or ``$THEANOMPI_TPU_COMPILATION_CACHE``; no-op when neither is
    set, returning None).

    Why: the measured ResNet-50 step compile is 39.3 s on the tunnel
    (BASELINE.md) — a third of a 10-minute TPU window.  With the cache
    on, a repeat window deserializes the executable instead of
    recompiling, so the queue's ladder and the serving warmup pay the
    compile once per (program, flags) pair, not once per process.  The
    cache key includes the XLA flags and jax version, so flag sweeps
    (tools/xla_sweep.py) never cross-contaminate.

    Exports the env var so subprocesses (run_tpu_queue children, the
    bench probe, spawned services) inherit the same cache directory.
    """
    cache_dir = cache_dir or os.environ.get(COMPILATION_CACHE_ENV)
    if not cache_dir:
        return None
    cache_dir = os.path.abspath(cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    try:
        # cache every compile the moment it costs anything: the default
        # min-compile-time gate (1 s) is fine, but tiny-entry skipping
        # would drop the many small jitted helpers the rules dispatch
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except AttributeError:  # older jax without the knob
        pass
    os.environ[COMPILATION_CACHE_ENV] = cache_dir
    return cache_dir


def divide_batches(n_samples: int, batch_size: int, drop_remainder: bool = True) -> int:
    """Number of batches per epoch (reference dropped ragged tails)."""
    if drop_remainder:
        return n_samples // batch_size
    return -(-n_samples // batch_size)


def scale_lr(lr: float, size: int, mode: str = "linear") -> float:
    """Linear LR scaling with worker count (the reference's ``scale_lr``)."""
    if mode == "linear":
        return lr * size
    if mode == "sqrt":
        return lr * (size ** 0.5)
    raise ValueError(f"unknown lr scaling mode {mode!r}")


#: optimizer families ``build_optimizer`` knows how to assemble.  The
#: reference era was SGD+momentum only (its layers lib built momentum
#: update rules by hand); the zoo adds the families large-batch TPU
#: recipes actually use (LARS for big-batch ResNet, AdamW for
#: transformers) — all lr-mutable via inject_hyperparams so
#: ``adjust_hyperp``/``set_learning_rate`` work uniformly.
OPTIMIZERS = ("sgd", "adam", "adamw", "rmsprop", "lars")


def build_optimizer(learning_rate: float, optimizer: str = "sgd",
                    momentum: float = 0.0, nesterov: bool = False,
                    weight_decay: float = 0.0, beta1: float = 0.9,
                    beta2: float = 0.999, eps: float = 1e-8,
                    rmsprop_decay: float = 0.9,
                    lars_trust_coefficient: float = 0.001):
    """Build the framework's optimizer chain from plain hyperparams —
    shared by TpuModel and the remote parameter service, which must
    rebuild a worker's optimizer from an init message (optax transforms
    hold closures and do not pickle, so the wire format is this kwargs
    dict; see ``TpuModel.optimizer_hyperparams``).

    Weight decay for sgd / adam / rmsprop is classic L2 added to the
    grads pre-update (coupled — for adaptive optimizers it rides
    through the normalization); adamw and lars apply their own
    *decoupled* decay directly to the params.
    """
    if optimizer not in OPTIMIZERS:
        raise ValueError(f"unknown optimizer {optimizer!r}; "
                         f"choose from {OPTIMIZERS}")

    def make(learning_rate):
        parts = []
        if weight_decay and optimizer in ("sgd", "adam", "rmsprop"):
            parts.append(optax.add_decayed_weights(weight_decay))
        if optimizer == "sgd":
            parts.append(optax.sgd(learning_rate, momentum=momentum or None,
                                   nesterov=nesterov))
        elif optimizer == "adam":
            parts.append(optax.adam(learning_rate, b1=beta1, b2=beta2,
                                    eps=eps))
        elif optimizer == "adamw":
            parts.append(optax.adamw(learning_rate, b1=beta1, b2=beta2,
                                     eps=eps, weight_decay=weight_decay))
        elif optimizer == "rmsprop":
            parts.append(optax.rmsprop(learning_rate, decay=rmsprop_decay,
                                       eps=eps, momentum=momentum or None))
        elif optimizer == "lars":
            parts.append(optax.lars(
                learning_rate, weight_decay=weight_decay,
                trust_coefficient=lars_trust_coefficient,
                momentum=momentum, nesterov=nesterov))
        return optax.chain(*parts)

    return optax.inject_hyperparams(make)(learning_rate=learning_rate)


def build_sgd_optimizer(learning_rate: float, momentum: float = 0.0,
                        nesterov: bool = False, weight_decay: float = 0.0):
    """Back-compat alias: the original SGD-only builder."""
    return build_optimizer(learning_rate, optimizer="sgd",
                           momentum=momentum, nesterov=nesterov,
                           weight_decay=weight_decay)


def set_learning_rate(opt_state: PyTree, lr: float) -> PyTree:
    """Return a copy of an ``optax.inject_hyperparams`` optimizer state
    with its learning rate rewritten — pure and structure-preserving, so
    feeding it back into the jitted step does not retrace (the TPU
    analogue of the reference mutating its shared ``lr`` variable in
    ``adjust_hyperp``)."""
    old = optax.tree_utils.tree_get(opt_state, "learning_rate")
    if old is None:
        raise ValueError(
            "opt_state has no 'learning_rate' hyperparam; wrap the "
            "optimizer in optax.inject_hyperparams to make lr mutable"
        )
    return optax.tree_utils.tree_set(
        opt_state, learning_rate=jnp.asarray(lr, dtype=jnp.asarray(old).dtype)
    )


def get_learning_rate(opt_state: PyTree) -> float | None:
    lr = optax.tree_utils.tree_get(opt_state, "learning_rate")
    return None if lr is None else float(lr)


# -- flat-vector view of a param pytree (the async rules ship params as
#    one contiguous buffer, like the reference's flattened GPU buffers) --


def tree_to_vector(tree: PyTree) -> tuple[np.ndarray, Any]:
    """Flatten a pytree into one contiguous uint8 byte vector.

    Byte-exact per leaf (no dtype upcast), so mixed fp32/bf16/int trees
    round-trip losslessly and the wire size is exactly the payload size.
    """
    leaves, treedef = jax.tree.flatten(tree)
    arrs = [np.asarray(l) for l in leaves]
    if arrs:
        flat = np.concatenate([a.ravel().view(np.uint8) if a.dtype == np.uint8
                               else np.frombuffer(a.tobytes(), np.uint8)
                               for a in arrs])
    else:
        flat = np.zeros(0, np.uint8)
    meta = (treedef, [(a.shape, a.dtype) for a in arrs])
    return flat, meta


def vector_to_tree(vec: np.ndarray, meta: Any) -> PyTree:
    treedef, shapes = meta
    leaves, off = [], 0
    for shape, dtype in shapes:
        nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        leaves.append(
            np.frombuffer(bytes(vec[off:off + nbytes]), dtype=dtype).reshape(shape)
        )
        off += nbytes
    return jax.tree.unflatten(treedef, leaves)


def tree_size(tree: PyTree) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(tree))


# -- npz param save/load (reference parity format, SURVEY.md §2.7) --


def _keypath_str(keypath) -> str:
    """Stable string key for one tree path (dict keys, sequence indices
    and attribute nodes — NamedTuples / flax.struct dataclasses)."""
    parts = []
    for k in keypath:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def save_params_npz(path: str, params: PyTree) -> None:
    flat = {
        _keypath_str(keypath): np.asarray(leaf)
        for keypath, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    np.savez(path, **flat)


def load_params_npz(path: str, like: PyTree) -> PyTree:
    with np.load(path) as data:
        flat_paths = jax.tree_util.tree_flatten_with_path(like)
        leaves = []
        for keypath, leaf in flat_paths[0]:
            key = _keypath_str(keypath)
            arr = data[key]
            if arr.shape != leaf.shape:
                raise ValueError(f"shape mismatch for {key}: {arr.shape} vs {leaf.shape}")
            leaves.append(arr.astype(leaf.dtype))
    return jax.tree.unflatten(flat_paths[1], leaves)
