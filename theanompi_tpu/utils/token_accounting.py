"""Token-throughput accounting shared by training and serving bench.

``tools/bench_lm.py`` (training tokens/s) and ``tools/bench_serving.py
--decode`` (served tokens/s) must compute the SAME quantity the same
way, or a "serving reaches X% of training throughput" claim silently
compares different arithmetic.  One helper, one definition:

* a **token** is one position of one sequence that the model produced
  or trained on — for training, ``steps * global_batch * seq_len``
  (every position of every sequence gets a loss); for decode serving,
  the number of GENERATED tokens (prompt positions are prefill work,
  not output — they are counted separately by the prefill histogram);
* **tokens/s** divides by the measurement wall window;
* **tokens/s/chip** divides further by the participating chip count —
  the BASELINE.md comparison axis (r3: 157k tok/s/chip).

Speculative decoding adds a second axis the two benches must also
agree on (``speculative_accounting``): a served token is an EMITTED
token — the accepted draft prefix plus the verify step's own argmax —
so ``tokens`` above is unchanged by speculation; REJECTED draft
tokens are compute spent, never output, and are excluded from both
the throughput number and the inter-token SLO histogram (as is each
stream's first token, which is queue+prefill latency — see
``decode/scheduler.py _emit_token``).
"""

from __future__ import annotations


def token_throughput(tokens: int, wall_s: float,
                     n_chips: int = 1) -> dict:
    """The canonical tokens/s record both bench tools embed.

    Returns ``{tokens, wall_s, tokens_per_sec, tokens_per_sec_per_chip,
    n_chips}`` — ``tokens_per_sec*`` are 0.0 for an empty window
    rather than a ZeroDivisionError (a bench that measured nothing
    should emit an honest zero, not crash after the run)."""
    tokens = int(tokens)
    wall_s = float(wall_s)
    n_chips = max(1, int(n_chips))
    rate = tokens / wall_s if wall_s > 0 else 0.0
    return {
        "tokens": tokens,
        "wall_s": wall_s,
        "n_chips": n_chips,
        "tokens_per_sec": rate,
        "tokens_per_sec_per_chip": rate / n_chips,
    }


def speculative_accounting(emitted: int, drafted: int,
                           accepted: int) -> dict:
    """The canonical speculative-decode record both the scheduler's
    ``stats()`` and ``bench_serving --decode`` embed.

    ``emitted`` — tokens actually produced (the throughput axis,
    identical to the non-speculative count for the same request);
    ``drafted`` — draft proposals made (k per sequence per round);
    ``accepted`` — proposals the verify step kept.  ``accept_rate`` is
    accepted/drafted (None before any speculation, not a fake 0.0)."""
    emitted, drafted = int(emitted), int(drafted)
    accepted = int(accepted)
    return {
        "emitted_tokens": emitted,
        "draft_tokens": drafted,
        "accepted_draft_tokens": accepted,
        "accept_rate": accepted / drafted if drafted else None,
    }
