"""Token-throughput accounting shared by training and serving bench.

``tools/bench_lm.py`` (training tokens/s) and ``tools/bench_serving.py
--decode`` (served tokens/s) must compute the SAME quantity the same
way, or a "serving reaches X% of training throughput" claim silently
compares different arithmetic.  One helper, one definition:

* a **token** is one position of one sequence that the model produced
  or trained on — for training, ``steps * global_batch * seq_len``
  (every position of every sequence gets a loss); for decode serving,
  the number of GENERATED tokens (prompt positions are prefill work,
  not output — they are counted separately by the prefill histogram);
* **tokens/s** divides by the measurement wall window;
* **tokens/s/chip** divides further by the participating chip count —
  the BASELINE.md comparison axis (r3: 157k tok/s/chip).
"""

from __future__ import annotations


def token_throughput(tokens: int, wall_s: float,
                     n_chips: int = 1) -> dict:
    """The canonical tokens/s record both bench tools embed.

    Returns ``{tokens, wall_s, tokens_per_sec, tokens_per_sec_per_chip,
    n_chips}`` — ``tokens_per_sec*`` are 0.0 for an empty window
    rather than a ZeroDivisionError (a bench that measured nothing
    should emit an honest zero, not crash after the run)."""
    tokens = int(tokens)
    wall_s = float(wall_s)
    n_chips = max(1, int(n_chips))
    rate = tokens / wall_s if wall_s > 0 else 0.0
    return {
        "tokens": tokens,
        "wall_s": wall_s,
        "n_chips": n_chips,
        "tokens_per_sec": rate,
        "tokens_per_sec_per_chip": rate / n_chips,
    }
