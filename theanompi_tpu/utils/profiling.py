"""Profiler integration — jax.profiler traces around the training loop.

The reference leaned on Theano's profiler plus the Recorder's wall
timers (SURVEY.md §5.1 — mount empty, no file:line).  The TPU
equivalent is XLA's own tracer: ``StepProfiler`` captures the first N
steps of a session into a TensorBoard-loadable trace (HLO timelines,
ICI collectives, host/device overlap), and per-step
``jax.profiler.StepTraceAnnotation`` markers (emitted by
``TpuModel.train_iter``) label each iteration in the timeline.

Enable by env (``THEANOMPI_TPU_PROFILE=/dir`` plus optional
``THEANOMPI_TPU_PROFILE_STEPS``, default 20) or by passing ``log_dir``
to ``run_bsp_session``.  View with TensorBoard's profile plugin or
``xprof``.
"""

from __future__ import annotations

import os

import jax


class StepProfiler:
    """Trace the first ``n_steps`` training iterations, then stop.

    No-op unless a log dir is configured, so the session loop can call
    it unconditionally.

    Also a context manager: ``with StepProfiler(dir):`` starts the
    capture on entry and guarantees ``stop()`` on exit — a crash
    mid-capture still flushes a loadable trace instead of losing the
    whole capture (``jax.profiler.stop_trace`` is what writes the
    files)."""

    def __init__(self, log_dir: str | None = None,
                 n_steps: int | None = None):
        self.log_dir = log_dir or os.environ.get("THEANOMPI_TPU_PROFILE")
        self.n_steps = (n_steps if n_steps is not None else
                        int(os.environ.get("THEANOMPI_TPU_PROFILE_STEPS",
                                           "20")))
        self._active = False
        self._done = False
        self._count = 0

    @property
    def enabled(self) -> bool:
        return bool(self.log_dir)

    def __enter__(self) -> "StepProfiler":
        self.maybe_start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()

    def maybe_start(self) -> None:
        if self.log_dir and not self._active and not self._done:
            jax.profiler.start_trace(self.log_dir)
            self._active = True

    def step(self) -> None:
        """Call once per training iteration."""
        if self._active:
            self._count += 1
            if self._count >= self.n_steps:
                self.stop()

    def stop(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
