"""Training recorder — calc/comm/wait section timers + metric curves.

Parity rebuild of the reference's ``Recorder`` (reference layout
``theanompi/lib/recorder.py``, SURVEY.md §2.10/§5.1 — mount empty, no
file:line): per-iteration wall timers for compute / exchange / wait
sections, running train loss+error, per-epoch val summaries,
images/sec, printed periodically and dumped to disk for plotting.

TPU-specific caveat built into the API: under ``jit`` the step call
returns before the device finishes (async dispatch), so naive wall
timers around the step measure dispatch, not compute.  ``end()``
therefore optionally blocks on a supplied array
(``jax.block_until_ready``) — the framework's BSP loop passes the
step's output metrics so 'calc' means device time, matching what the
reference's CUDA-synchronous Theano functions measured.  Structured
output is JSONL (one record per epoch) rather than the reference's
pickled lists.
"""

from __future__ import annotations

import json
import os
import time
from collections import defaultdict
from typing import Any

import numpy as np

from theanompi_tpu import monitor


def device_fence(tree: Any) -> None:
    """Reliable device fence for timing (VERDICT r1 #6).

    ``jax.block_until_ready`` is the natural fence, but the
    experimental axon TPU plugin's implementation can return before
    the device has finished (bench.py discovered this in round 1 and
    fenced with a value readback).  So: block first, then read small
    leaves back outright (the hot-loop ``block_on`` is always the
    step's scalar metrics — a few bytes) and one element of any large
    leaf, which forces the host to wait for the producing program on
    every backend.
    """
    import jax

    leaves = [l for l in jax.tree.leaves(tree)
              if isinstance(l, jax.Array)]
    jax.block_until_ready(leaves)
    for l in leaves:
        if l.size <= 16:
            np.asarray(l)
        else:
            shard = l.addressable_shards[0].data
            np.asarray(shard.ravel()[:1])


class Recorder:
    SECTIONS = ("calc", "comm", "wait", "load")

    def __init__(self, rank: int = 0, size: int = 1,
                 print_freq: int = 40, save_dir: str | None = None,
                 flops_per_sample: float | None = None,
                 images_are_global: bool = True):
        self.rank = rank
        self.size = size
        self.print_freq = print_freq
        self.save_dir = save_dir
        #: trained FLOPs per sample (model-declared) — lets the epoch
        #: record report achieved TFLOP/s per shard, the honest input
        #: to any MFU claim (docs/DESIGN.md's measured denominators)
        self.flops_per_sample = flops_per_sample
        #: True (BSP): n_images counts the GLOBAL batch, divide by
        #: size for the per-shard rate.  False (async rules): each
        #: worker's recorder counts only its own images
        self.images_are_global = images_are_global
        self._t0: float | None = None
        self.epoch_time: dict[str, float] = defaultdict(float)
        self.all_time: dict[str, float] = defaultdict(float)
        self.train_losses: list[float] = []
        self.train_errors: list[float] = []
        self.epoch_records: list[dict] = []
        self.n_images = 0
        self._epoch_start = time.monotonic()
        self.epoch = 0

    # -- section timing (reference API shape: start() ... end('calc')) --

    def start(self) -> None:
        self._t0 = time.monotonic()

    def end(self, section: str, block_on: Any = None) -> float:
        """Close the open section.  If ``block_on`` is a jax array (or
        pytree), block until it is ready first so device time is charged
        to this section rather than to whoever touches the value next (via
        ``device_fence`` — truthful on the axon plugin too)."""
        if section not in self.SECTIONS:
            raise ValueError(f"unknown section {section!r}")
        if self._t0 is None:
            raise RuntimeError("Recorder.end() without start()")
        if block_on is not None:
            device_fence(block_on)
        dt = time.monotonic() - self._t0
        self._t0 = None
        self.epoch_time[section] += dt
        self.all_time[section] += dt
        # thin client of the telemetry registry: every closed section
        # also lands in the section-time histogram (count+sum there are
        # the per-section span totals; no-op when monitoring is off)
        monitor.observe("recorder/section_ms", dt * 1e3, section=section,
                        rank=str(self.rank))
        return dt

    # -- metric accumulation --

    def train_metrics(self, loss: float, error: float, n_images: int) -> None:
        self.train_losses.append(float(loss))
        self.train_errors.append(float(error))
        self.n_images += int(n_images)

    def print_train_info(self, it: int) -> None:
        # cadence is the caller's business (models flush pending device
        # metrics every print_freq iterations and then call this)
        if self.rank != 0 or self.print_freq <= 0:
            return
        window = self.train_losses[-self.print_freq:]
        werr = self.train_errors[-self.print_freq:]
        print(
            f"[epoch {self.epoch} it {it}] "
            f"loss {np.mean(window):.4f} err {np.mean(werr):.4f} "
            f"calc {self.epoch_time['calc']:.1f}s "
            f"load {self.epoch_time['load']:.1f}s "
            f"wait {self.epoch_time['wait']:.1f}s",
            flush=True,
        )

    def epoch_summary(self, epoch: int, val_loss: float | None = None,
                      val_error: float | None = None) -> dict:
        wall = time.monotonic() - self._epoch_start
        rec = {
            "epoch": epoch,
            "wall_time_s": round(wall, 3),
            "images_per_sec": round(self.n_images / wall, 2) if wall > 0 else 0.0,
            "tflops_per_shard": (
                round(self.n_images / wall
                      / (max(self.size, 1) if self.images_are_global
                         else 1)
                      * self.flops_per_sample / 1e12, 2)
                if wall > 0 and self.flops_per_sample else None),
            "train_loss": float(np.mean(self.train_losses)) if self.train_losses else None,
            "train_error": float(np.mean(self.train_errors)) if self.train_errors else None,
            "val_loss": None if val_loss is None else float(val_loss),
            "val_error": None if val_error is None else float(val_error),
            "time": {k: round(self.epoch_time[k], 3) for k in self.SECTIONS},
        }
        self.epoch_records.append(rec)
        monitor.inc("recorder/epochs_total", rank=str(self.rank))
        monitor.set_gauge("recorder/images_per_sec",
                          rec["images_per_sec"], rank=str(self.rank))
        if self.rank == 0:
            print(
                f"== epoch {epoch}: {rec['images_per_sec']} img/s, "
                f"train_loss {rec['train_loss']}, val_error {rec['val_error']}, "
                f"calc/comm/wait/load = "
                + "/".join(f"{rec['time'][k]}" for k in self.SECTIONS),
                flush=True,
            )
        if self.save_dir is not None:
            self.save(self.save_dir)
        # reset per-epoch accumulators
        self.epoch_time = defaultdict(float)
        self.train_losses, self.train_errors = [], []
        self.n_images = 0
        self._epoch_start = time.monotonic()
        self.epoch = epoch + 1
        return rec

    # -- persistence --

    def save(self, save_dir: str) -> str:
        os.makedirs(save_dir, exist_ok=True)
        path = os.path.join(save_dir, f"record_rank{self.rank}.jsonl")
        with open(path, "w") as f:
            for rec in self.epoch_records:
                f.write(json.dumps(rec) + "\n")
        return path

    def load(self, save_dir: str) -> None:
        path = os.path.join(save_dir, f"record_rank{self.rank}.jsonl")
        if os.path.exists(path):
            with open(path) as f:
                self.epoch_records = [json.loads(l) for l in f if l.strip()]
            if self.epoch_records:
                self.epoch = self.epoch_records[-1]["epoch"] + 1
                # rebuild cumulative section totals from the per-epoch
                # records, so a resumed run's all_time reports honest
                # lifetime totals instead of restarting from zero
                self.all_time = defaultdict(float)
                for rec in self.epoch_records:
                    for section, dt in rec.get("time", {}).items():
                        self.all_time[section] += float(dt)
