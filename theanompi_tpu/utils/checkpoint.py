"""Checkpoint / resume.

Parity rebuild of the reference's per-epoch save/resume (rank-0 npz/
pickle of ``self.params`` + recorder state, resume via a
``load_epoch``-style config — SURVEY.md §5.4; mount empty, no
file:line), built on Orbax.

Cross-rule invariant (SURVEY.md §5.4): a checkpoint written by any rule
is a valid init for any other — we store one canonical pytree
``{params, opt_state, model_state, epoch, step}``; EASGD saves its
center params in the same slot, so an EASGD center checkpoint restores
cleanly into a BSP run and vice versa.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

PyTree = Any


class Checkpointer:
    """Thin synchronous Orbax wrapper with epoch-numbered directories."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, epoch: int, payload: PyTree, force: bool = False) -> None:
        # Move to host numpy so the checkpoint is device-layout agnostic.
        payload = jax.tree.map(np.asarray, payload)
        self._mgr.save(epoch, args=ocp.args.StandardSave(payload), force=force)
        self._mgr.wait_until_finished()

    def latest_epoch(self) -> int | None:
        return self._mgr.latest_step()

    def kept_epochs(self) -> set[int]:
        """Epochs still on disk after max_to_keep pruning — callers
        with sidecar files (GOSGD per-worker params) prune to match."""
        return set(self._mgr.all_steps())

    def restore(self, epoch: int | None = None, like: PyTree | None = None) -> PyTree:
        if epoch is None:
            epoch = self.latest_epoch()
        if epoch is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        if like is not None:
            like = jax.tree.map(np.asarray, like)
            return self._mgr.restore(epoch, args=ocp.args.StandardRestore(like))
        return self._mgr.restore(epoch)

    def close(self) -> None:
        self._mgr.close()
