"""Checkpoint / resume.

Parity rebuild of the reference's per-epoch save/resume (rank-0 npz/
pickle of ``self.params`` + recorder state, resume via a
``load_epoch``-style config — SURVEY.md §5.4; mount empty, no
file:line), built on Orbax.

Cross-rule invariant (SURVEY.md §5.4): a checkpoint written by any rule
is a valid init for any other — we store one canonical pytree
``{params, opt_state, model_state, epoch, step}``; EASGD saves its
center params in the same slot, so an EASGD center checkpoint restores
cleanly into a BSP run and vice versa.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

PyTree = Any


class Checkpointer:
    """Thin Orbax wrapper with epoch-numbered directories.

    Saves are ASYNC by default: ``save`` snapshots the state to host
    (the device copy — unavoidable) and returns while Orbax writes the
    files in the background, so the next epoch trains during the I/O;
    the previous write is fenced at the start of the next ``save``, in
    ``restore``/``latest_epoch``/``kept_epochs``, and in ``close``.
    Pass ``async_save=False`` for the reference's fully-synchronous
    per-epoch semantics."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = os.path.abspath(directory)
        self.async_save = async_save
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def _fence(self) -> None:
        """Join any in-flight background write, surfacing its error
        with checkpoint context (an async write failure otherwise
        reads like an unrelated crash at the next epoch)."""
        try:
            self._mgr.wait_until_finished()
        except Exception as e:
            raise RuntimeError(
                f"background checkpoint write to {self.directory} "
                f"failed: {e}") from e

    def save(self, epoch: int, payload: PyTree, force: bool = False) -> None:
        self._fence()  # fence any in-flight write

        # np.array (not asarray): device arrays copy either way, but a
        # host-numpy payload must ALSO be copied or the async write
        # races with caller mutations.  Arrays spanning non-addressable
        # devices (ZeRO/TP state under multi-controller) CANNOT be
        # fetched to one host — leave them as jax.Arrays; Orbax saves
        # distributed arrays natively (every process calls save, each
        # writing its addressable shards).
        #
        # CONTRACT (load-bearing): Orbax's async save copies device
        # buffers to host BEFORE save() returns — only the file I/O is
        # backgrounded — so the caller's next train step may freely
        # DONATE these buffers (parallel/bsp.py donate_argnums=(0,)).
        # tests/test_multihost.py::test_two_process_async_save_survives_
        # donation exercises exactly that seam; if an Orbax upgrade ever
        # makes the d2h copy lazy, that test fails rather than this
        # comment silently lying.
        def snap(l):
            if isinstance(l, jax.Array) and not l.is_fully_addressable:
                return l
            return np.array(l)

        payload = jax.tree.map(snap, payload)
        self._mgr.save(epoch, args=ocp.args.StandardSave(payload), force=force)
        if not self.async_save:
            self._mgr.wait_until_finished()

    def latest_epoch(self) -> int | None:
        self._fence()
        return self._mgr.latest_step()

    def kept_epochs(self) -> set[int]:
        """Epochs still on disk after max_to_keep pruning — callers
        with sidecar files (GOSGD per-worker params) prune to match."""
        self._fence()
        return set(self._mgr.all_steps())

    def restore(self, epoch: int | None = None, like: PyTree | None = None) -> PyTree:
        self._fence()
        if epoch is None:
            epoch = self.latest_epoch()
        if epoch is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        if like is not None:
            # distributed template leaves keep their sharding so the
            # restore lands shard-by-shard on each process
            like = jax.tree.map(
                lambda l: l if (isinstance(l, jax.Array)
                                and not l.is_fully_addressable)
                else np.asarray(l), like)
            return self._mgr.restore(epoch, args=ocp.args.StandardRestore(like))
        return self._mgr.restore(epoch)

    def close(self) -> None:
        # A failed final write is itself data loss — surface it.  When
        # close runs in a finally during another exception's unwind,
        # Python's implicit chaining keeps BOTH visible ('during
        # handling of the above exception...'), so nothing is masked.
        self._fence()
        self._mgr.close()
