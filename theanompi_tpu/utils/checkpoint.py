"""Checkpoint / resume.

Parity rebuild of the reference's per-epoch save/resume (rank-0 npz/
pickle of ``self.params`` + recorder state, resume via a
``load_epoch``-style config — SURVEY.md §5.4; mount empty, no
file:line), built on Orbax.

Cross-rule invariant (SURVEY.md §5.4): a checkpoint written by any rule
is a valid init for any other — we store one canonical pytree
``{params, opt_state, model_state, epoch, step}``; EASGD saves its
center params in the same slot, so an EASGD center checkpoint restores
cleanly into a BSP run and vice versa.

Integrity (docs/RESILIENCE.md): every *completed* save gets a
``manifest_{epoch}.json`` beside its step directory (per-file sizes +
sha256, queued at fence time — after the async write has landed — and
digested on a background worker so the training thread never pays the
hash);
``restore_latest_verified`` restores the newest checkpoint that passes
verification, falling back to older kept epochs when the latest is
corrupt — a truncated checkpoint costs one epoch, not the resume.
"""

from __future__ import annotations

import os
import threading
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from theanompi_tpu.resilience import faults, recovery
from theanompi_tpu.resilience.retry import RetryPolicy

PyTree = Any


class Checkpointer:
    """Thin Orbax wrapper with epoch-numbered directories.

    Saves are ASYNC by default: ``save`` snapshots the state to host
    (the device copy — unavoidable) and returns while Orbax writes the
    files in the background, so the next epoch trains during the I/O;
    the previous write is fenced at the start of the next ``save``, in
    ``restore``/``latest_epoch``/``kept_epochs``, and in ``close``.
    Pass ``async_save=False`` for the reference's fully-synchronous
    per-epoch semantics.

    ``read_only=True`` is the SERVING-READER mode (docs/SERVING.md): a
    process that only ever loads — an inference server watching a
    trainer's (or exporter's) directory — must not contend with the
    writer or mutate anything it reads.  A read-only Checkpointer
    refuses ``save``, writes no manifests and prunes none, and its
    ``quarantine_epoch`` is a no-op (``restore_latest_verified`` then
    falls back PAST a corrupt epoch but leaves the corrupt files in
    place for the owning writer to deal with).  A serving load leaves
    the directory byte-identical — pinned by
    tests/test_checkpoint.py::test_read_only_load_leaves_dir_byte_identical."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True, integrity: bool = True,
                 retry: RetryPolicy | None = None,
                 read_only: bool = False):
        self.directory = os.path.abspath(directory)
        self.read_only = read_only
        if read_only and not os.path.isdir(self.directory):
            raise FileNotFoundError(
                f"read-only Checkpointer: {self.directory} does not "
                "exist (a reader must not create the writer's dir)")
        self.async_save = async_save
        self.integrity = integrity
        # transient-I/O retry on the RESTORE read path (a shared-
        # filesystem hiccup must not kill a resume).  Deliberately NOT
        # used around wait_until_finished: orbax clears its stored
        # async-write exception after raising it once, so a retried
        # fence would report a failed write as success — the exact
        # data-loss masking the fence exists to prevent.
        self._retry = retry or RetryPolicy(
            max_attempts=3, base_delay=0.2, max_delay=2.0,
            name="checkpoint_restore")
        #: epochs saved but whose manifest is not yet written (the
        #: async write may still be in flight)
        self._unverified: set[int] = set()
        # manifest digests run on a background worker (sha256 of a
        # full checkpoint is seconds at ResNet scale — not something
        # the training thread pays per epoch); drained only where
        # manifests are actually consumed (restore_latest_verified,
        # close, sync-mode save)
        import queue as _queue

        self._manifest_q: _queue.Queue = _queue.Queue()
        self._manifest_thread: threading.Thread | None = None
        self._max_to_keep = max_to_keep
        if not read_only:
            os.makedirs(self.directory, exist_ok=True)
        self._mgr = self._make_manager()

    def _make_manager(self) -> ocp.CheckpointManager:
        return ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self._max_to_keep,
                # a reader must not create (or otherwise touch) the
                # writer's directory; max_to_keep pruning only happens
                # on save, which read-only mode refuses
                create=not self.read_only,
            ),
        )

    def _fence(self) -> None:
        """Join any in-flight background write, surfacing its error
        with checkpoint context (an async write failure otherwise
        reads like an unrelated crash at the next epoch); then queue
        integrity manifests for every write that just landed."""
        try:
            self._mgr.wait_until_finished()
        except Exception as e:
            raise RuntimeError(
                f"background checkpoint write to {self.directory} "
                f"failed: {e}") from e
        if self.integrity and not self.read_only:
            self._sync_manifests()

    def _sync_manifests(self) -> None:
        """Queue manifest digests for completed saves; prune manifests
        of epochs ``max_to_keep`` dropped.  Runs after a successful
        fence, so every step dir queued here is fully written."""
        kept = set(self._mgr.all_steps())
        for epoch in sorted(self._unverified):
            self._unverified.discard(epoch)
            if epoch not in kept:
                continue  # already pruned
            step_dir = recovery.find_step_dir(self.directory, epoch)
            if step_dir is not None:
                self._manifest_q.put((epoch, step_dir))
                self._ensure_manifest_worker()
        recovery.prune_manifests(self.directory, kept)

    def _ensure_manifest_worker(self) -> None:
        if (self._manifest_thread is None
                or not self._manifest_thread.is_alive()):
            self._manifest_thread = threading.Thread(
                target=self._manifest_loop, daemon=True,
                name="checkpoint-manifests")
            self._manifest_thread.start()

    def _manifest_loop(self) -> None:
        while True:
            item = self._manifest_q.get()
            if item is None:  # close() sentinel
                self._manifest_q.task_done()
                return
            epoch, step_dir = item
            try:
                recovery.write_manifest(self.directory, epoch, step_dir)
                # fault plane: corrupt the epoch AFTER its manifest is
                # written from the good files — the bit-rot simulation
                # the recovery tests drive (docs/RESILIENCE.md)
                if faults.fire("checkpoint", epoch=epoch) == "truncate":
                    _truncate_largest_file(step_dir)
            except OSError:
                pass  # a full disk must not kill anything
            except Exception as e:
                # the worker must survive ANYTHING (incl. a fault spec
                # with a 'raise' action at this site) — a dead worker
                # would hang _drain_manifests' Queue.join forever
                import sys

                print(f"[resilience] manifest worker: "
                      f"{type(e).__name__}: {e}", file=sys.stderr,
                      flush=True)
            finally:
                self._manifest_q.task_done()

    def _drain_manifests(self) -> None:
        """Block until every queued manifest is on disk — called where
        manifests are consumed, never on the per-epoch save path."""
        if self.integrity and not self.read_only:
            self._manifest_q.join()

    def save(self, epoch: int, payload: PyTree, force: bool = False) -> None:
        if self.read_only:
            raise RuntimeError(
                f"Checkpointer({self.directory!r}) is read-only "
                "(serving reader); refusing save")
        self._fence()  # fence any in-flight write

        # np.array (not asarray): device arrays copy either way, but a
        # host-numpy payload must ALSO be copied or the async write
        # races with caller mutations.  Arrays spanning non-addressable
        # devices (ZeRO/TP state under multi-controller) CANNOT be
        # fetched to one host — leave them as jax.Arrays; Orbax saves
        # distributed arrays natively (every process calls save, each
        # writing its addressable shards).
        #
        # CONTRACT (load-bearing): Orbax's async save copies device
        # buffers to host BEFORE save() returns — only the file I/O is
        # backgrounded — so the caller's next train step may freely
        # DONATE these buffers (parallel/bsp.py donate_argnums=(0,)).
        # tests/test_multihost.py::test_two_process_async_save_survives_
        # donation exercises exactly that seam; if an Orbax upgrade ever
        # makes the d2h copy lazy, that test fails rather than this
        # comment silently lying.
        def snap(l):
            if isinstance(l, jax.Array) and not l.is_fully_addressable:
                return l
            return np.array(l)

        payload = jax.tree.map(snap, payload)
        # orbax 0.7: saving an already-existing step is SILENTLY
        # skipped (and force=True refuses outright) — happens when a
        # supervised restart re-reaches an epoch it saved pre-crash.
        # A skipped save must not be queued for a manifest, or the
        # fence would re-bless whatever files are already on disk.
        skipped = int(epoch) in set(self._mgr.all_steps())
        self._mgr.save(epoch, args=ocp.args.StandardSave(payload), force=force)
        if not skipped:
            self._unverified.add(int(epoch))
        if not self.async_save:
            # the reference's fully-synchronous semantics: write AND
            # manifest are on disk when save returns
            self._mgr.wait_until_finished()
            if self.integrity:
                self._sync_manifests()
                self._drain_manifests()

    def latest_epoch(self) -> int | None:
        self._fence()
        return self._mgr.latest_step()

    def kept_epochs(self) -> set[int]:
        """Epochs still on disk after max_to_keep pruning — callers
        with sidecar files (GOSGD per-worker params) prune to match."""
        self._fence()
        return set(self._mgr.all_steps())

    def restore(self, epoch: int | None = None, like: PyTree | None = None) -> PyTree:
        self._fence()
        if epoch is None:
            epoch = self.latest_epoch()
        if epoch is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        if like is not None:
            # distributed template leaves keep their sharding so the
            # restore lands shard-by-shard on each process
            like = jax.tree.map(
                lambda l: l if (isinstance(l, jax.Array)
                                and not l.is_fully_addressable)
                else np.asarray(l), like)
            # transient read-I/O retry (resilience.retry): a shared-FS
            # hiccup retries; a corrupt checkpoint (ValueError & co.)
            # fails fast for restore_latest_verified's fallback
            return self._retry.call(
                self._mgr.restore, epoch,
                args=ocp.args.StandardRestore(like),
                site="checkpoint/restore")
        # template-less restore still names the handler explicitly: a
        # FRESH manager (reopened dir, read-only serving reader) has no
        # registry entry from a prior save and would otherwise refuse
        return self._retry.call(self._mgr.restore, epoch,
                                args=ocp.args.StandardRestore(),
                                site="checkpoint/restore")

    def quarantine_epoch(self, epoch: int) -> str | None:
        """Move a PROVEN-corrupt epoch's step dir (and manifest) aside
        so (a) the resumed run's save of that epoch actually writes —
        orbax silently skips (or, with force, refuses) a save to an
        existing step — and (b) no later manifest pass re-blesses the
        corrupt files.  Recreates the manager so its step cache
        forgets the quarantined epoch.  Returns the quarantine path
        (None when there was nothing to move).

        Read-only mode: a no-op returning None — the serving reader's
        ``restore_latest_verified`` still falls back past the corrupt
        epoch (recovery.py treats None as 'left in place'), but only
        the owning WRITER may move its files."""
        if self.read_only:
            return None
        step_dir = recovery.find_step_dir(self.directory, epoch)
        if step_dir is None:
            return None
        # a SUBDIRECTORY, not a sibling rename: orbax's step scanner
        # parses trailing digits out of top-level names (corrupt_1
        # would still read as step 1 and crash the manager's scan)
        qdir = os.path.join(self.directory, "quarantine")
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, str(int(epoch)))
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(qdir, f"{int(epoch)}.{n}")
        os.rename(step_dir, dst)
        mpath = recovery.manifest_path(self.directory, epoch)
        try:
            os.unlink(mpath)
        except OSError:
            pass
        self._mgr.close()
        self._mgr = self._make_manager()
        return dst

    def restore_latest_verified(self, like: PyTree | None = None
                                ) -> tuple[int | None, PyTree | None]:
        """(epoch, payload) of the newest checkpoint that verifies
        against its manifest AND restores; falls back to older kept
        epochs when the latest is corrupt (resilience.recovery).
        (None, None) when nothing is restorable."""
        self._fence()
        self._drain_manifests()  # verification consumes the manifests
        return recovery.restore_latest_verified(self, like=like)

    def close(self) -> None:
        # A failed final write is itself data loss — surface it.  When
        # close runs in a finally during another exception's unwind,
        # Python's implicit chaining keeps BOTH visible ('during
        # handling of the above exception...'), so nothing is masked.
        self._fence()
        self._drain_manifests()  # manifests must outlive this process
        if (self._manifest_thread is not None
                and self._manifest_thread.is_alive()):
            self._manifest_q.put(None)  # release the worker thread
            self._manifest_thread.join(timeout=5)
        self._mgr.close()


def _truncate_largest_file(step_dir: str) -> None:
    """Fault-plane helper: halve the largest file in a step dir (the
    'checkpoint write landed corrupt' simulation)."""
    best, best_size = None, -1
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            full = os.path.join(root, name)
            size = os.path.getsize(full)
            if size > best_size:
                best, best_size = full, size
    if best is not None and best_size > 0:
        with open(best, "r+b") as f:
            f.truncate(best_size // 2)
