"""DecodeSession — jitted prefill/decode programs over one page pool.

The autoregressive counterpart of ``serving/export.py
InferenceSession``: one session owns the replica's page pool
(decode/kvcache.py) and exactly TWO families of compiled programs,
keyed by the bucket discipline that keeps steady state recompile-free:

* **prefill** — one program per padded PROMPT-LENGTH bucket
  (``prefill_buckets``, powers of two): the whole prompt runs through
  the sliding-window full forward (decode/model.py) and its last
  ``window`` positions' K/V scatter into the sequence's freshly
  allocated pages; returns the last real token's logits (the first
  decode step for free).
* **decode** — one program per DECODE-BATCH bucket
  (``serving.batcher.default_buckets(max_seqs)``): the active
  sequences are packed to the front, padded to the bucket with
  inactive rows (whose page writes route to a dropped id), and one
  token advances for every live sequence in a single device step over
  a fixed-shape page pool.

Both donate the pool buffers (``donate_argnums``) — the cache updates
in place, XLA never holds two pools.  Both count their own traces by a
plain Python increment INSIDE the traced body (re-tracing re-runs the
Python), which is the compile-counter tests/test_decode.py pins at
"steady state = zero new compiles" — the same trick as
``exchange/traces_total``.

Host-side sequence state (page rows, lengths, the free-page pool) is
owned by the replica's single scheduler thread
(decode/scheduler.py) — no locks by design.  ``swap`` (hot reload) is
the only cross-thread entry and uses the ``InferenceSession`` pattern:
one published ``(version, params)`` tuple, snapshot-read per step, so
an in-flight step finishes on the params it started with.

Quantized params (serving/export.py ``weight_dtype``) work in both
modes: pass a dequantized tree (``load_export(..., dequantize=True)``,
the default) or the raw quantized tree — ``dequantize_tree`` runs
inside the jitted body, so int8 weights stay int8 on device and
rematerialize per step (the replicas-per-chip lever).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.decode import kvcache
from theanompi_tpu.decode.model import (
    decode_block,
    embed_tokens,
    final_logits,
    full_forward,
)
from theanompi_tpu.serving.batcher import default_buckets, pick_bucket
from theanompi_tpu.serving.export import dequantize_tree


def default_prefill_buckets(max_len: int,
                            cap: int = 512) -> tuple[int, ...]:
    """Powers of two from 8 up to min(cap, max_len) — a handful of
    prompt shapes covering every admissible prompt."""
    out = []
    b = 8
    while b <= min(int(cap), int(max_len)):
        out.append(b)
        b *= 2
    return tuple(out)


class _Seq:
    """One live sequence's host-side cache bookkeeping (scheduler-
    thread owned)."""

    __slots__ = ("page_row", "length")

    def __init__(self, page_row: np.ndarray, length: int):
        self.page_row = page_row
        self.length = int(length)


class DecodeSession:
    """Paged-KV token generation for one exported transformer."""

    def __init__(self, model, params=None, version: int = 0,
                 page_size: int = 16, pages_per_seq: int = 8,
                 max_seqs: int = 8,
                 prefill_buckets: tuple[int, ...] | None = None,
                 donate: bool = True):
        module = model.module
        for field in ("n_layers", "n_heads", "d_model", "max_len"):
            if not hasattr(module, field):
                raise ValueError(
                    f"{type(module).__name__} is not a decode-capable "
                    f"transformer (missing {field}); decode serves "
                    "the TransformerLM family only")
        self.model = model
        self.n_layers = int(module.n_layers)
        self.n_heads = int(module.n_heads)
        self.d_model = int(module.d_model)
        self.max_len = int(module.max_len)
        self.dtype = jnp.dtype(module.dtype)
        self.cfg = kvcache.CacheConfig(
            n_layers=self.n_layers, n_heads=self.n_heads,
            d_head=self.d_model // self.n_heads, page_size=page_size,
            pages_per_seq=pages_per_seq, max_seqs=max_seqs,
            dtype=self.dtype.name)
        self.window = self.cfg.window
        self.prefill_buckets = tuple(sorted(set(
            int(b) for b in (prefill_buckets or
                             default_prefill_buckets(self.max_len)))))
        if not self.prefill_buckets \
                or self.prefill_buckets[0] < 1 \
                or self.prefill_buckets[-1] > self.max_len:
            raise ValueError(
                f"prefill buckets {self.prefill_buckets} must be >= 1 "
                f"and <= max_len {self.max_len}")
        self.decode_buckets = default_buckets(int(max_seqs))
        self.max_prompt = self.prefill_buckets[-1]

        params = params if params is not None else model.state.params
        # one-tuple publish, snapshot-read (InferenceSession pattern):
        # an in-flight prefill/decode finishes on the params it read
        self._live = (int(version), self._place(params))
        self._swap_lock = make_lock("DecodeSession._swap_lock")

        # scheduler-thread-owned device + host cache state
        self._ck, self._cv = kvcache.init_pages(self.cfg)
        self.pool = kvcache.PagePool(self.cfg)

        #: traces per program family — incremented at TRACE time inside
        #: the jitted bodies; the steady-state-zero-recompiles pin
        self.compiles = {"prefill": 0, "decode": 0}
        self._prefill = jax.jit(
            self._prefill_fn, donate_argnums=(1, 2) if donate else ())
        self._decode = jax.jit(
            self._decode_fn, donate_argnums=(1, 2) if donate else ())

    # -- params ---------------------------------------------------------

    @staticmethod
    def _place(tree):
        return jax.tree.map(jnp.asarray, tree)

    @property
    def version(self) -> int:
        return self._live[0]

    def swap(self, version: int, params, model_state=None) -> bool:
        """Publish new weights (hot reload / restart-from-export).
        Monotonic like ``InferenceSession.swap``; the cache is NOT
        reset — in-flight sequences continue, their next tokens come
        from the new weights (docs/SERVING.md decode reload note).
        ``model_state`` is accepted for Replica-interface parity; the
        LM family has none."""
        del model_state
        with self._swap_lock:
            if int(version) < self._live[0]:
                return False
            self._live = (int(version), self._place(params))
            return True

    # -- jitted programs ------------------------------------------------

    def _prefill_fn(self, params, k_pages, v_pages, tokens, length,
                    page_row):
        self.compiles["prefill"] += 1      # trace-time counter
        p = dequantize_tree(params)
        logits, ks, vs = full_forward(p, tokens, self.n_layers,
                                      self.n_heads, self.dtype,
                                      window=self.window)
        ps, pps = self.cfg.page_size, self.cfg.pages_per_seq
        hd = (self.n_heads, self.cfg.d_head)
        ring_k = jnp.stack([
            kvcache.ring_from_prompt(k[0], length, self.window)
            for k in ks]).reshape(self.n_layers, pps, ps, *hd)
        ring_v = jnp.stack([
            kvcache.ring_from_prompt(v[0], length, self.window)
            for v in vs]).reshape(self.n_layers, pps, ps, *hd)
        k_pages = k_pages.at[:, page_row].set(ring_k, mode="drop")
        v_pages = v_pages.at[:, page_row].set(ring_v, mode="drop")
        return k_pages, v_pages, logits[0, length - 1]

    def _decode_fn(self, params, k_pages, v_pages, tokens, lengths,
                   page_rows, active):
        self.compiles["decode"] += 1       # trace-time counter
        p = dequantize_tree(params)
        pos = jnp.minimum(lengths, self.max_len - 1)
        x = embed_tokens(p, tokens, pos)[:, None, :].astype(self.dtype)
        mask = kvcache.cache_mask(lengths, self.window)
        k_new, v_new = [], []
        for layer in range(self.n_layers):
            kc = kvcache.gather_layer(k_pages[layer], page_rows)
            vc = kvcache.gather_layer(v_pages[layer], page_rows)
            x, kn, vn = decode_block(p[f"Block_{layer}"], x, kc, vc,
                                     mask, self.n_heads, self.dtype)
            k_new.append(kn)
            v_new.append(vn)
        # all writes are for THIS token, so they land after every
        # layer's (pre-write) gather — one batched scatter per pool
        k_pages = kvcache.write_token_all(k_pages, page_rows, lengths,
                                          active, jnp.stack(k_new))
        v_pages = kvcache.write_token_all(v_pages, page_rows, lengths,
                                          active, jnp.stack(v_new))
        return k_pages, v_pages, final_logits(p, x, self.dtype)[:, 0]

    # -- scheduler-facing host API (single scheduler thread) ------------

    def can_admit(self) -> bool:
        return self.pool.free_pages >= self.cfg.pages_per_seq

    def admit(self, prompt: np.ndarray) -> tuple[_Seq, np.ndarray]:
        """Allocate pages, prefill the prompt, return the new sequence
        and the last real token's f32 logits (V,)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        t = prompt.shape[0]
        if not 1 <= t <= self.max_prompt:
            raise ValueError(
                f"prompt length {t} outside [1, {self.max_prompt}] "
                "(largest prefill bucket)")
        page_row = self.pool.alloc_seq()
        if page_row is None:
            raise RuntimeError("admit() without free pages — the "
                               "scheduler must check can_admit() first")
        bucket = pick_bucket(t, self.prefill_buckets)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :t] = prompt
        _, params = self._live          # one-read snapshot
        try:
            self._ck, self._cv, logits = self._prefill(
                params, self._ck, self._cv, jnp.asarray(tokens),
                jnp.int32(t), jnp.asarray(page_row))
        except Exception:
            # a failed prefill must not leak the sequence's pages
            self.pool.free_seq(page_row)
            raise
        return _Seq(page_row, t), np.asarray(jax.device_get(logits))

    def decode(self, seqs: list[_Seq],
               tokens: np.ndarray) -> np.ndarray:
        """One decode step for every sequence in ``seqs`` (their
        freshly sampled ``tokens``, one each) — packed and padded to a
        decode bucket.  Returns f32 logits (len(seqs), V) for the NEXT
        token; each sequence's length advances by one."""
        n = len(seqs)
        if not 1 <= n <= self.cfg.max_seqs:
            raise ValueError(f"{n} sequences outside "
                             f"[1, {self.cfg.max_seqs}]")
        bucket = pick_bucket(n, self.decode_buckets)
        toks = np.zeros((bucket,), np.int32)
        lens = np.zeros((bucket,), np.int32)
        rows = np.full((bucket, self.cfg.pages_per_seq),
                       self.cfg.n_pages, np.int32)
        active = np.zeros((bucket,), bool)
        for i, s in enumerate(seqs):
            toks[i] = tokens[i]
            lens[i] = s.length
            rows[i] = s.page_row
            active[i] = True
        _, params = self._live          # one-read snapshot
        self._ck, self._cv, logits = self._decode(
            params, self._ck, self._cv, jnp.asarray(toks),
            jnp.asarray(lens), jnp.asarray(rows), jnp.asarray(active))
        for s in seqs:
            s.length += 1
        return np.asarray(jax.device_get(logits))[:n]

    def release(self, seq: _Seq) -> None:
        self.pool.free_seq(seq.page_row)

    def reset_cache(self) -> None:
        """Fresh page pool + allocator (restart-from-export path): a
        failed step may have consumed the donated pool buffers, so the
        replica restarts from zeroed pages — live sequences were
        already failed and released by the scheduler."""
        self._ck, self._cv = kvcache.init_pages(self.cfg)
        self.pool = kvcache.PagePool(self.cfg)

    def warmup(self) -> None:
        """Compile the smallest prefill and decode programs before the
        port binds (the rest compile once at first use — still 'once
        ever' per bucket, which is what the counter pins)."""
        _, params = self._live
        drop_row = np.full((self.cfg.pages_per_seq,), self.cfg.n_pages,
                           np.int32)
        tokens = np.zeros((1, self.prefill_buckets[0]), np.int32)
        self._ck, self._cv, _ = self._prefill(
            params, self._ck, self._cv, jnp.asarray(tokens),
            jnp.int32(1), jnp.asarray(drop_row))
        bucket = self.decode_buckets[0]
        rows = np.full((bucket, self.cfg.pages_per_seq),
                       self.cfg.n_pages, np.int32)
        self._ck, self._cv, _ = self._decode(
            params, self._ck, self._cv,
            jnp.zeros((bucket,), jnp.int32),
            jnp.zeros((bucket,), jnp.int32), jnp.asarray(rows),
            jnp.zeros((bucket,), bool))
