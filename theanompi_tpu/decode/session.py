"""DecodeSession — jitted prefill/decode programs over one page pool.

The autoregressive counterpart of ``serving/export.py
InferenceSession``: one session owns the replica's page pool
(decode/kvcache.py) and exactly TWO families of compiled programs,
keyed by the bucket discipline that keeps steady state recompile-free:

* **prefill** — one program per padded PROMPT-LENGTH bucket
  (``prefill_buckets``, powers of two): the whole prompt runs through
  the sliding-window full forward (decode/model.py) and its last
  ``window`` positions' K/V scatter into the sequence's freshly
  allocated pages; returns the last real token's logits (the first
  decode step for free).
* **decode** — one program per DECODE-BATCH bucket
  (``serving.batcher.default_buckets(max_seqs)``): the active
  sequences are packed to the front, padded to the bucket with
  inactive rows (whose page writes route to a dropped id), and one
  token advances for every live sequence in a single device step over
  a fixed-shape page pool.

Three more families serve the two token-throughput multipliers
(docs/SERVING.md "Speculative decode" / "Prefix cache"), all riding
the same bucket discipline so steady state stays recompile-free:

* **verify** (target role) — one program per decode bucket at a fixed
  ``k``: k drafted tokens + the pending one run as a (k+1)-token
  chunk in ONE bucketed step; the accept count is computed INSIDE the
  jit (longest matching prefix of target argmax vs drafts) and the
  K/V scatter is count-masked, so rejected tokens are never written —
  accept/reject is data, not shape, and never recompiles.
* **propose** / **commit** (draft role) — ``propose`` runs k greedy
  draft steps in one program, keeping the new K/V in SCRATCH outputs
  (plus one extra pass for the k-th draft's K/V, so a full accept
  leaves no cache gap); ``commit`` scatters the accepted prefix of
  the scratch into the draft pool after the verdict.  The draft pool
  therefore only ever holds accepted history — "rolling back past
  rejected tokens" is simply not writing them.
* **extend** — prefix-cache hit prefill: the prompt SUFFIX (padded to
  a prefill bucket) attends the shared pages through the ring and
  scatters only its own K/V into freshly allocated pages.
* **prefill_batch** — the admission plane's multi-sequence program:
  S prompt chunks (cold prompts starting at 0, prefix-cache hits at
  their shared-prefix length) run as ONE (decode-bucket ×
  prefill-bucket) chunk step with per-row start offsets, per-row ring
  masks, and count-masked page scatters — the ``write_tokens_all``
  discipline lifted one axis up, so bucket-padding rows never write.
  ``admit_batch`` replaces N serial ``admit`` calls with one program
  call; ``warmup_prefill_batch`` compiles every (S, C) bucket pair up
  front because occupancy varies run to run.

Page sharing is host-side (refcounted ``PagePool`` + ``PrefixCache``,
decode/kvcache.py) with copy-on-write: ``_cow_prepare`` runs before
every writing program and replaces any still-shared page the write
would touch with a private device copy (the fixed-shape ``cow_copy``
program).

Both donate the pool buffers (``donate_argnums``) — the cache updates
in place, XLA never holds two pools.  Both count their own traces by a
plain Python increment INSIDE the traced body (re-tracing re-runs the
Python), which is the compile-counter tests/test_decode.py pins at
"steady state = zero new compiles" — the same trick as
``exchange/traces_total``.

Host-side sequence state (page rows, lengths, the free-page pool) is
owned by the replica's single scheduler thread
(decode/scheduler.py) — no locks by design.  ``swap`` (hot reload) is
the only cross-thread entry and uses the ``InferenceSession`` pattern:
one published ``(version, params)`` tuple, snapshot-read per step, so
an in-flight step finishes on the params it started with.

Quantized params (serving/export.py ``weight_dtype``) work in both
modes: pass a dequantized tree (``load_export(..., dequantize=True)``,
the default) or the raw quantized tree — ``dequantize_tree`` runs
inside the jitted body, so int8 weights stay int8 on device and
rematerialize per step (the replicas-per-chip lever).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.decode import kvcache
from theanompi_tpu.decode.model import (
    chunk_block,
    decode_block,
    embed_tokens,
    final_logits,
    full_forward,
)
from theanompi_tpu.serving.batcher import default_buckets, pick_bucket
from theanompi_tpu.serving.export import dequantize_tree

#: pairs per COW copy program call (fixed shape — one compile ever);
#: bursts larger than this just loop the same program
COPY_BUCKET = 8


def default_prefill_buckets(max_len: int,
                            cap: int = 512) -> tuple[int, ...]:
    """Powers of two from 8 up to min(cap, max_len) — a handful of
    prompt shapes covering every admissible prompt."""
    out = []
    b = 8
    while b <= min(int(cap), int(max_len)):
        out.append(b)
        b *= 2
    return tuple(out)


class _Seq:
    """One live sequence's host-side cache bookkeeping (scheduler-
    thread owned)."""

    __slots__ = ("page_row", "length")

    def __init__(self, page_row: np.ndarray, length: int):
        self.page_row = page_row
        self.length = int(length)


class DecodeSession:
    """Paged-KV token generation for one exported transformer."""

    def __init__(self, model, params=None, version: int = 0,
                 page_size: int = 16, pages_per_seq: int = 8,
                 max_seqs: int = 8,
                 prefill_buckets: tuple[int, ...] | None = None,
                 donate: bool = True, prefix_cache: bool = True):
        module = model.module
        for field in ("n_layers", "n_heads", "d_model", "max_len"):
            if not hasattr(module, field):
                raise ValueError(
                    f"{type(module).__name__} is not a decode-capable "
                    f"transformer (missing {field}); decode serves "
                    "the TransformerLM family only")
        self.model = model
        self.n_layers = int(module.n_layers)
        self.n_heads = int(module.n_heads)
        self.d_model = int(module.d_model)
        self.max_len = int(module.max_len)
        self.dtype = jnp.dtype(module.dtype)
        self.cfg = kvcache.CacheConfig(
            n_layers=self.n_layers, n_heads=self.n_heads,
            d_head=self.d_model // self.n_heads, page_size=page_size,
            pages_per_seq=pages_per_seq, max_seqs=max_seqs,
            dtype=self.dtype.name)
        self.window = self.cfg.window
        self.prefill_buckets = tuple(sorted(set(
            int(b) for b in (prefill_buckets or
                             default_prefill_buckets(self.max_len)))))
        if not self.prefill_buckets \
                or self.prefill_buckets[0] < 1 \
                or self.prefill_buckets[-1] > self.max_len:
            raise ValueError(
                f"prefill buckets {self.prefill_buckets} must be >= 1 "
                f"and <= max_len {self.max_len}")
        self.decode_buckets = default_buckets(int(max_seqs))
        self.max_prompt = self.prefill_buckets[-1]

        params = params if params is not None else model.state.params
        # one-tuple publish, snapshot-read (InferenceSession pattern):
        # an in-flight prefill/decode finishes on the params it read
        self._live = (int(version), self._place(params))
        self._swap_lock = make_lock("DecodeSession._swap_lock")

        # scheduler-thread-owned device + host cache state
        self._ck, self._cv = kvcache.init_pages(self.cfg)
        self.pool = kvcache.PagePool(self.cfg)
        #: cross-request prefix cache (None = sharing disabled)
        self.prefix_cache = (kvcache.PrefixCache(self.pool, self.window)
                             if prefix_cache else None)
        #: device page copies made to un-share a page before a write
        self.cow_copies = 0
        #: draft role: (scratch_k, scratch_v, bucket, n) pending commit
        self._scratch = None

        #: traces per program family — incremented at TRACE time inside
        #: the jitted bodies; the steady-state-zero-recompiles pin
        self.compiles = {"prefill": 0, "decode": 0, "verify": 0,
                         "propose": 0, "commit": 0, "extend": 0,
                         "prefill_batch": 0, "cow_copy": 0, "adopt": 0}
        #: fleet prefix-cache client (decode/fleetcache.py), attached
        #: by the replica when --fleet-cache points at an authority;
        #: None = local-only sharing
        self.fleet = None
        self._prefill = jax.jit(
            self._prefill_fn, donate_argnums=(1, 2) if donate else ())
        self._prefill_batch = jax.jit(
            self._prefill_batch_fn,
            donate_argnums=(1, 2) if donate else ())
        self._decode = jax.jit(
            self._decode_fn, donate_argnums=(1, 2) if donate else ())
        self._verify = jax.jit(
            self._verify_fn, donate_argnums=(1, 2) if donate else ())
        # propose READS the pool (no writes) — nothing donated, the
        # live pool buffers must survive the call for verify/commit
        self._propose = jax.jit(self._propose_fn,
                                static_argnames=("k",))
        self._commit = jax.jit(
            self._commit_fn, donate_argnums=(0, 1) if donate else ())
        self._extend = jax.jit(
            self._extend_fn, donate_argnums=(1, 2) if donate else ())
        self._copy = jax.jit(
            self._copy_fn, donate_argnums=(0, 1) if donate else ())
        # migrated pages arrive host-side (wire frames) and must
        # survive a failed scatter for the refusal path — only the
        # pool is donated
        self._adopt = jax.jit(
            self._adopt_fn, donate_argnums=(0, 1) if donate else ())

    # -- params ---------------------------------------------------------

    @staticmethod
    def _place(tree):
        return jax.tree.map(jnp.asarray, tree)

    @property
    def version(self) -> int:
        return self._live[0]

    def swap(self, version: int, params, model_state=None) -> bool:
        """Publish new weights (hot reload / restart-from-export).
        Monotonic like ``InferenceSession.swap``; the cache is NOT
        reset — in-flight sequences continue, their next tokens come
        from the new weights (docs/SERVING.md decode reload note).
        ``model_state`` is accepted for Replica-interface parity; the
        LM family has none."""
        del model_state
        with self._swap_lock:
            if int(version) < self._live[0]:
                return False
            self._live = (int(version), self._place(params))
            return True

    # -- jitted programs ------------------------------------------------

    def _prefill_fn(self, params, k_pages, v_pages, tokens, length,
                    page_row):
        self.compiles["prefill"] += 1      # trace-time counter
        p = dequantize_tree(params)
        logits, ks, vs = full_forward(p, tokens, self.n_layers,
                                      self.n_heads, self.dtype,
                                      window=self.window)
        ps, pps = self.cfg.page_size, self.cfg.pages_per_seq
        hd = (self.n_heads, self.cfg.d_head)
        ring_k = jnp.stack([
            kvcache.ring_from_prompt(k[0], length, self.window)
            for k in ks]).reshape(self.n_layers, pps, ps, *hd)
        ring_v = jnp.stack([
            kvcache.ring_from_prompt(v[0], length, self.window)
            for v in vs]).reshape(self.n_layers, pps, ps, *hd)
        k_pages = k_pages.at[:, page_row].set(ring_k, mode="drop")
        v_pages = v_pages.at[:, page_row].set(ring_v, mode="drop")
        return k_pages, v_pages, logits[0, length - 1]

    def _decode_fn(self, params, k_pages, v_pages, tokens, lengths,
                   page_rows, active):
        self.compiles["decode"] += 1       # trace-time counter
        p = dequantize_tree(params)
        pos = jnp.minimum(lengths, self.max_len - 1)
        x = embed_tokens(p, tokens, pos)[:, None, :].astype(self.dtype)
        mask = kvcache.cache_mask(lengths, self.window)
        k_new, v_new = [], []
        for layer in range(self.n_layers):
            kc = kvcache.gather_layer(k_pages[layer], page_rows)
            vc = kvcache.gather_layer(v_pages[layer], page_rows)
            x, kn, vn = decode_block(p[f"Block_{layer}"], x, kc, vc,
                                     mask, self.n_heads, self.dtype)
            k_new.append(kn)
            v_new.append(vn)
        # all writes are for THIS token, so they land after every
        # layer's (pre-write) gather — one batched scatter per pool
        k_pages = kvcache.write_token_all(k_pages, page_rows, lengths,
                                          active, jnp.stack(k_new))
        v_pages = kvcache.write_token_all(v_pages, page_rows, lengths,
                                          active, jnp.stack(v_new))
        return k_pages, v_pages, final_logits(p, x, self.dtype)[:, 0]

    def _verify_fn(self, params, k_pages, v_pages, tokens, lengths,
                   page_rows, active):
        """Target role: tokens (S, k+1) = [pending, d_1..d_k] run as
        one chunk; accept count and the count-masked K/V writes happen
        in-jit, so accept/reject boundaries are data, never shapes."""
        self.compiles["verify"] += 1       # trace-time counter
        p = dequantize_tree(params)
        c = tokens.shape[1]
        pos = jnp.minimum(
            lengths[:, None] + jnp.arange(c, dtype=jnp.int32),
            self.max_len - 1)
        x = embed_tokens(p, tokens, pos).astype(self.dtype)
        ring_mask = kvcache.chunk_cache_mask(lengths, c, self.window)
        k_new, v_new = [], []
        for layer in range(self.n_layers):
            kc = kvcache.gather_layer(k_pages[layer], page_rows)
            vc = kvcache.gather_layer(v_pages[layer], page_rows)
            x, kn, vn = chunk_block(p[f"Block_{layer}"], x, kc, vc,
                                    ring_mask, self.n_heads,
                                    self.dtype, window=self.window)
            k_new.append(kn)
            v_new.append(vn)
        y = jnp.argmax(final_logits(p, x, self.dtype),
                       axis=-1).astype(jnp.int32)          # (S, k+1)
        # longest matching prefix: d_i accepted iff it equals the
        # target's own argmax y_{i-1} and every earlier draft matched
        match = (tokens[:, 1:] == y[:, :-1]).astype(jnp.int32)
        m = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        counts = jnp.where(active, m + 1, 0).astype(jnp.int32)
        k_pages = kvcache.write_tokens_all(k_pages, page_rows, lengths,
                                           counts, jnp.stack(k_new))
        v_pages = kvcache.write_tokens_all(v_pages, page_rows, lengths,
                                           counts, jnp.stack(v_new))
        return k_pages, v_pages, y, counts

    def _propose_fn(self, params, k_pages, v_pages, tokens, lengths,
                    page_rows, active, *, k):
        """Draft role: k greedy one-token steps unrolled in ONE
        program, new K/V accumulated in scratch (token i attends the
        ring + scratch tokens 0..i-1 + itself) and RETURNED, never
        written — plus one extra K/V-only pass for the k-th draft, so
        a full accept leaves the draft cache gap-free.  Padding rows
        produce garbage the commit's zero count drops."""
        self.compiles["propose"] += 1      # trace-time counter
        del active
        p = dequantize_tree(params)
        s_ = tokens.shape[0]
        ring_mask = kvcache.chunk_cache_mask(lengths, k + 1, self.window)
        rk = [kvcache.gather_layer(k_pages[layer], page_rows)
              for layer in range(self.n_layers)]
        rv = [kvcache.gather_layer(v_pages[layer], page_rows)
              for layer in range(self.n_layers)]
        scratch_k = [[] for _ in range(self.n_layers)]
        scratch_v = [[] for _ in range(self.n_layers)]
        drafts = []
        tok = tokens
        for i in range(k + 1):
            pos = jnp.minimum(lengths + i, self.max_len - 1)
            x = embed_tokens(p, tok, pos)[:, None, :].astype(self.dtype)
            for layer in range(self.n_layers):
                if i:
                    kc = jnp.concatenate(
                        [rk[layer], jnp.stack(scratch_k[layer], 1)], 1)
                    vc = jnp.concatenate(
                        [rv[layer], jnp.stack(scratch_v[layer], 1)], 1)
                    m = jnp.concatenate(
                        [ring_mask[:, i], jnp.ones((s_, i), bool)], 1)
                else:
                    kc, vc, m = rk[layer], rv[layer], ring_mask[:, 0]
                x, kn, vn = decode_block(p[f"Block_{layer}"], x, kc,
                                         vc, m, self.n_heads,
                                         self.dtype)
                scratch_k[layer].append(kn)
                scratch_v[layer].append(vn)
            if i < k:
                tok = jnp.argmax(final_logits(p, x, self.dtype)[:, 0],
                                 axis=-1).astype(jnp.int32)
                drafts.append(tok)
        return (jnp.stack(drafts, 1),                     # (S, k)
                jnp.stack([jnp.stack(s, 1) for s in scratch_k]),
                jnp.stack([jnp.stack(s, 1) for s in scratch_v]))

    def _commit_fn(self, k_pages, v_pages, scratch_k, scratch_v,
                   lengths, page_rows, counts):
        """Draft role: scatter the verdict's accepted prefix of the
        propose scratch into the pool (count-masked, like verify)."""
        self.compiles["commit"] += 1       # trace-time counter
        k_pages = kvcache.write_tokens_all(k_pages, page_rows, lengths,
                                           counts, scratch_k)
        v_pages = kvcache.write_tokens_all(v_pages, page_rows, lengths,
                                           counts, scratch_v)
        return k_pages, v_pages

    def _extend_fn(self, params, k_pages, v_pages, tokens, start,
                   length, page_row):
        """Prefix-cache hit prefill: the prompt SUFFIX (one sequence,
        padded to a prefill bucket) attends the shared prefix through
        the ring and scatters only its own positions' K/V — into the
        freshly allocated suffix pages, never the shared ones."""
        self.compiles["extend"] += 1       # trace-time counter
        p = dequantize_tree(params)
        c = tokens.shape[1]
        starts = jnp.reshape(start, (1,)).astype(jnp.int32)
        pos = jnp.minimum(
            starts[:, None] + jnp.arange(c, dtype=jnp.int32),
            self.max_len - 1)
        x = embed_tokens(p, tokens, pos).astype(self.dtype)
        ring_mask = kvcache.chunk_cache_mask(starts, c, self.window)
        rows = page_row[None]
        k_new, v_new = [], []
        for layer in range(self.n_layers):
            kc = kvcache.gather_layer(k_pages[layer], rows)
            vc = kvcache.gather_layer(v_pages[layer], rows)
            x, kn, vn = chunk_block(p[f"Block_{layer}"], x, kc, vc,
                                    ring_mask, self.n_heads,
                                    self.dtype, window=self.window)
            k_new.append(kn)
            v_new.append(vn)
        logits = final_logits(p, x, self.dtype)
        counts = jnp.reshape(length, (1,)).astype(jnp.int32)
        k_pages = kvcache.write_tokens_all(k_pages, rows, starts,
                                           counts, jnp.stack(k_new))
        v_pages = kvcache.write_tokens_all(v_pages, rows, starts,
                                           counts, jnp.stack(v_new))
        return k_pages, v_pages, logits[0, length - 1]

    def _prefill_batch_fn(self, params, k_pages, v_pages, tokens,
                          starts, counts, page_rows):
        """Batched prefill/extend: S sequences' prompt chunks run as
        ONE bucketed chunk step.  ``tokens``: (S, C) — each row the
        tokens from its start offset (a cold prompt's whole prompt at
        start 0, a prefix-cache hit's suffix at its shared-prefix
        length), zero-padded; ``starts``/``counts``: (S,).  A cold row
        sees an all-false ring mask (nothing stored yet) and the
        in-chunk sliding-window causal mask alone — masked scores
        exp-underflow to exact zeros, so each row's math is
        byte-identical to its serial prefill/extend program.  Writes
        are count-masked per row (``write_tokens_all``): bucket-
        padding rows (count 0) and window-evicted positions of a
        window-exceeding cold prompt never reach the pool."""
        self.compiles["prefill_batch"] += 1  # trace-time counter
        p = dequantize_tree(params)
        c = tokens.shape[1]
        starts = starts.astype(jnp.int32)
        pos = jnp.minimum(
            starts[:, None] + jnp.arange(c, dtype=jnp.int32),
            self.max_len - 1)
        x = embed_tokens(p, tokens, pos).astype(self.dtype)
        ring_mask = kvcache.chunk_cache_mask(starts, c, self.window)
        k_new, v_new = [], []
        for layer in range(self.n_layers):
            kc = kvcache.gather_layer(k_pages[layer], page_rows)
            vc = kvcache.gather_layer(v_pages[layer], page_rows)
            x, kn, vn = chunk_block(p[f"Block_{layer}"], x, kc, vc,
                                    ring_mask, self.n_heads,
                                    self.dtype, window=self.window)
            k_new.append(kn)
            v_new.append(vn)
        logits = final_logits(p, x, self.dtype)            # (S, C, V)
        counts = counts.astype(jnp.int32)
        k_pages = kvcache.write_tokens_all(k_pages, page_rows, starts,
                                           counts, jnp.stack(k_new))
        v_pages = kvcache.write_tokens_all(v_pages, page_rows, starts,
                                           counts, jnp.stack(v_new))
        last = jnp.clip(counts - 1, 0, c - 1)[:, None, None]
        return (k_pages, v_pages,
                jnp.take_along_axis(logits, last, axis=1)[:, 0])

    def _copy_fn(self, k_pages, v_pages, src, dst):
        """Copy-on-write: duplicate pages ``src[i] -> dst[i]`` in both
        pools (fixed COPY_BUCKET pairs; padding writes to the dropped
        page id)."""
        self.compiles["cow_copy"] += 1     # trace-time counter
        k_pages = k_pages.at[:, dst].set(k_pages[:, src], mode="drop")
        v_pages = v_pages.at[:, dst].set(v_pages[:, src], mode="drop")
        return k_pages, v_pages

    def _adopt_fn(self, k_pages, v_pages, k_new, v_new, page_row):
        """Page migration (decode/migrate.py): scatter one migrated
        sequence's pages — ``(layers, pages_per_seq, page_size, heads,
        d_head)`` per pool, the prefill ring layout verbatim — into
        freshly allocated pages.  Fixed shape (always a full page row),
        so the program compiles ONCE ever and a disaggregated decode
        replica's steady state stays recompile-free."""
        self.compiles["adopt"] += 1        # trace-time counter
        k_pages = k_pages.at[:, page_row].set(k_new, mode="drop")
        v_pages = v_pages.at[:, page_row].set(v_new, mode="drop")
        return k_pages, v_pages

    # -- scheduler-facing host API (single scheduler thread) ------------

    def can_admit(self, n: int = 1) -> bool:
        """Whether ``n`` more sequences could allocate full page rows
        (conservative for prefix-cache hits, which alias part of
        theirs)."""
        free = self.pool.free_pages
        if self.prefix_cache is not None:
            # LRU eviction under allocation pressure frees cache-only
            # pages (_alloc_pages), so they count as admissible
            free += self.prefix_cache.evictable_pages()
        return free >= int(n) * self.cfg.pages_per_seq

    def _alloc_pages(self, n: int) -> list[int] | None:
        """Allocate with eviction pressure: a full pool evicts prefix-
        cache LRU entries until the allocation fits or the cache is
        dry (the ring/free-list discipline extended to shared pages)."""
        while True:
            got = self.pool.alloc(n)
            if got is not None:
                return got
            if self.prefix_cache is None or not len(self.prefix_cache):
                return None
            self.prefix_cache.evict_lru()

    def _cow_prepare(self, seqs: list[_Seq], span: int) -> None:
        """Copy-on-write fence before a program that writes positions
        ``[length, length+span)``: every touched page still shared
        (refcount > 1) is swapped for a private device copy first, so
        a write can never reach a page another sequence or the prefix
        cache still reads.  Evicting cache entries for the copy's page
        may drop the LAST other reference — then no copy is needed at
        all (the page just became private)."""
        ps = self.cfg.page_size
        src, dst = [], []
        for s in seqs:
            touched = sorted({(p % self.window) // ps
                              for p in range(s.length, s.length + span)})
            for idx in touched:
                pid = int(s.page_row[idx])
                while self.pool.refcount(pid) > 1:
                    got = self.pool.alloc(1)
                    if got is None:
                        if (self.prefix_cache is not None
                                and len(self.prefix_cache)):
                            self.prefix_cache.evict_lru()
                            continue
                        raise RuntimeError(
                            "page pool exhausted during copy-on-write")
                    src.append(pid)
                    dst.append(got[0])
                    self.pool.decref([pid])
                    s.page_row[idx] = got[0]
                    self.cow_copies += 1
                    break
        for i in range(0, len(src), COPY_BUCKET):
            sb = np.zeros(COPY_BUCKET, np.int32)
            db = np.full(COPY_BUCKET, self.cfg.n_pages, np.int32)
            chunk = src[i:i + COPY_BUCKET]
            sb[:len(chunk)] = chunk
            db[:len(chunk)] = dst[i:i + COPY_BUCKET]
            self._ck, self._cv = self._copy(
                self._ck, self._cv, jnp.asarray(sb), jnp.asarray(db))

    def admit(self, prompt: np.ndarray) -> tuple[_Seq, np.ndarray]:
        """Allocate pages, prefill the prompt, return the new sequence
        and the last real token's f32 logits (V,).

        With the prefix cache on, a prompt starting with a cached
        page-aligned prefix ALIASES the shared pages (refcount++) and
        prefills only the suffix (the ``extend`` program); either way
        the prompt's own page-aligned prefixes are registered for the
        next stream."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        t = prompt.shape[0]
        if not 1 <= t <= self.max_prompt:
            raise ValueError(
                f"prompt length {t} outside [1, {self.max_prompt}] "
                "(largest prefill bucket)")
        _, params = self._live          # one-read snapshot
        hit = self._lookup_prefix(prompt)
        if hit is not None:
            # adopt the shared pages BEFORE any allocation that could
            # evict the entry (and free them) out from under us
            self.pool.incref(hit.pages)
            fresh = self._alloc_pages(
                self.cfg.pages_per_seq - len(hit.pages))
            if fresh is None:
                self.pool.decref(hit.pages)
                raise RuntimeError(
                    "admit() without free pages — the scheduler must "
                    "check can_admit() first")
            page_row = np.asarray(list(hit.pages) + fresh, np.int32)
            start, suffix = hit.n_tokens, t - hit.n_tokens
            bucket = pick_bucket(suffix, self.prefill_buckets)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :suffix] = prompt[start:]
            try:
                self._ck, self._cv, logits = self._extend(
                    params, self._ck, self._cv, jnp.asarray(tokens),
                    jnp.int32(start), jnp.int32(suffix),
                    jnp.asarray(page_row))
            except Exception:
                self.pool.decref(page_row)
                raise
        else:
            got = self._alloc_pages(self.cfg.pages_per_seq)
            if got is None:
                raise RuntimeError(
                    "admit() without free pages — the scheduler must "
                    "check can_admit() first")
            page_row = np.asarray(got, np.int32)
            bucket = pick_bucket(t, self.prefill_buckets)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :t] = prompt
            try:
                self._ck, self._cv, logits = self._prefill(
                    params, self._ck, self._cv, jnp.asarray(tokens),
                    jnp.int32(t), jnp.asarray(page_row))
            except Exception:
                # a failed prefill must not leak the sequence's pages
                self.pool.free_seq(page_row)
                raise
        if self.prefix_cache is not None:
            self.prefix_cache.insert(prompt, page_row)
        if hit is None:
            self._fleet_register(prompt, page_row)
        return _Seq(page_row, t), np.asarray(jax.device_get(logits))

    def _lookup_prefix(self, prompt: np.ndarray):
        """Local prefix-cache lookup, falling back to the fleet cache
        authority when one is attached: a fleet hit adopts the shipped
        pages locally (``adopt_prefix``) and re-resolves, so a remote
        prefix becomes an ordinary local hit — all downstream sharing
        (incref, COW, eviction) is the local discipline."""
        if self.prefix_cache is None:
            return None
        hit = self.prefix_cache.lookup(prompt)
        if hit is None and self.fleet is not None \
                and self.fleet.fetch(self, prompt):
            hit = self.prefix_cache.lookup(prompt)
        return hit

    def _fleet_register(self, prompt: np.ndarray, page_row) -> None:
        """Offer a just-prefilled COLD prompt's longest page-aligned
        proper prefix to the fleet cache authority (best effort: the
        client counts transport errors, never raises — registration
        must not fail an admission)."""
        if self.fleet is None or self.prefix_cache is None:
            return
        t = int(prompt.shape[0])
        if t > self.window:
            return                      # prefilled through eviction
        q = (t - 1) // self.cfg.page_size
        if q < 1:
            return
        self.fleet.register(self, prompt[:q * self.cfg.page_size],
                            [int(p) for p in page_row[:q]])

    def admit_batch(self, prompts) -> list[tuple[_Seq, np.ndarray]]:
        """Admit up to ``max_seqs`` prompts in ONE batched
        prefill/extend program call — cold prompts and prefix-cache
        hit suffixes batch together (both are "chunk forward from a
        start offset").  Returns one ``(seq, last-token logits)`` pair
        per prompt, in order; each row's output is byte-identical to
        what a serial :meth:`admit` of the same prompt against the
        same cache state would return.

        Page accounting is per row with full unwind: any row's
        allocation failure (or a failed program) drops every
        already-taken reference, so a failed batch leaks nothing.  No
        COW fence is needed — every write lands in pages allocated at
        refcount 1 inside this call (shared hit pages are only read)."""
        n = len(prompts)
        if not 1 <= n <= self.cfg.max_seqs:
            raise ValueError(
                f"{n} prompts outside [1, {self.cfg.max_seqs}]")
        prompts = [np.asarray(p, np.int32).reshape(-1)
                   for p in prompts]
        for p in prompts:
            if not 1 <= p.shape[0] <= self.max_prompt:
                raise ValueError(
                    f"prompt length {p.shape[0]} outside "
                    f"[1, {self.max_prompt}] (largest prefill bucket)")
        if n == 1:
            # a singleton rides the serial families (already warm) —
            # the (n_seqs=1, token) batched variants would double the
            # program inventory for an identical result
            return [self.admit(prompts[0])]
        _, params = self._live          # one-read snapshot
        rows: list[tuple] = []  # (prompt, page_row, start, suffix, cold)
        try:
            for prompt in prompts:
                t = prompt.shape[0]
                hit = self._lookup_prefix(prompt)
                if hit is not None:
                    # adopt shared pages BEFORE any allocation that
                    # could evict the entry (same order as admit)
                    self.pool.incref(hit.pages)
                    fresh = self._alloc_pages(
                        self.cfg.pages_per_seq - len(hit.pages))
                    if fresh is None:
                        self.pool.decref(hit.pages)
                        raise RuntimeError(
                            "admit_batch() without free pages — the "
                            "scheduler must check can_admit(n) first")
                    page_row = np.asarray(list(hit.pages) + fresh,
                                          np.int32)
                    rows.append((prompt, page_row, hit.n_tokens,
                                 t - hit.n_tokens, False))
                else:
                    got = self._alloc_pages(self.cfg.pages_per_seq)
                    if got is None:
                        raise RuntimeError(
                            "admit_batch() without free pages — the "
                            "scheduler must check can_admit(n) first")
                    rows.append((prompt, np.asarray(got, np.int32),
                                 0, t, True))
        except Exception:
            for _, page_row, *_ in rows:
                self.pool.decref(page_row)
            raise
        sbucket = pick_bucket(n, self.decode_buckets)
        cbucket = pick_bucket(max(r[3] for r in rows),
                              self.prefill_buckets)
        toks = np.zeros((sbucket, cbucket), np.int32)
        starts = np.zeros((sbucket,), np.int32)
        counts = np.zeros((sbucket,), np.int32)
        prow = np.full((sbucket, self.cfg.pages_per_seq),
                       self.cfg.n_pages, np.int32)
        for i, (prompt, page_row, start, suffix, _) in enumerate(rows):
            toks[i, :suffix] = prompt[start:]
            starts[i] = start
            counts[i] = suffix
            prow[i] = page_row
        try:
            self._ck, self._cv, logits = self._prefill_batch(
                params, self._ck, self._cv, jnp.asarray(toks),
                jnp.asarray(starts), jnp.asarray(counts),
                jnp.asarray(prow))
        except Exception:
            for _, page_row, *_ in rows:
                self.pool.decref(page_row)
            raise
        logits = np.asarray(jax.device_get(logits))
        out = []
        for i, (prompt, page_row, _, _, cold) in enumerate(rows):
            if self.prefix_cache is not None:
                self.prefix_cache.insert(prompt, page_row)
            if cold:
                self._fleet_register(prompt, page_row)
            out.append((_Seq(page_row, prompt.shape[0]), logits[i]))
        return out

    def decode(self, seqs: list[_Seq],
               tokens: np.ndarray) -> np.ndarray:
        """One decode step for every sequence in ``seqs`` (their
        freshly sampled ``tokens``, one each) — packed and padded to a
        decode bucket.  Returns f32 logits (len(seqs), V) for the NEXT
        token; each sequence's length advances by one."""
        n = len(seqs)
        if not 1 <= n <= self.cfg.max_seqs:
            raise ValueError(f"{n} sequences outside "
                             f"[1, {self.cfg.max_seqs}]")
        self._cow_prepare(seqs, 1)
        bucket = pick_bucket(n, self.decode_buckets)
        toks = np.zeros((bucket,), np.int32)
        lens = np.zeros((bucket,), np.int32)
        rows = np.full((bucket, self.cfg.pages_per_seq),
                       self.cfg.n_pages, np.int32)
        active = np.zeros((bucket,), bool)
        for i, s in enumerate(seqs):
            toks[i] = tokens[i]
            lens[i] = s.length
            rows[i] = s.page_row
            active[i] = True
        _, params = self._live          # one-read snapshot
        self._ck, self._cv, logits = self._decode(
            params, self._ck, self._cv, jnp.asarray(toks),
            jnp.asarray(lens), jnp.asarray(rows), jnp.asarray(active))
        for s in seqs:
            s.length += 1
        return np.asarray(jax.device_get(logits))[:n]

    def verify(self, seqs: list[_Seq], pending: np.ndarray,
               drafts: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Target role: check each sequence's k drafted tokens in ONE
        bucketed step.  ``pending``: (n,) the last emitted token per
        sequence (decode would feed the same); ``drafts``: (n, k).
        Returns (y (n, k+1) — the target's own greedy token at every
        chunk position — and counts (n,) = accepted drafts + 1).  The
        caller emits ``y[i, :counts[i]]``; each sequence's length
        advances by its count (the K/V of exactly those tokens were
        written)."""
        n = len(seqs)
        drafts = np.asarray(drafts, np.int32).reshape(n, -1)
        k1 = drafts.shape[1] + 1
        if not 1 <= n <= self.cfg.max_seqs:
            raise ValueError(f"{n} sequences outside "
                             f"[1, {self.cfg.max_seqs}]")
        if k1 > self.window:
            raise ValueError(f"speculation chunk {k1} exceeds the "
                             f"ring window {self.window}")
        self._cow_prepare(seqs, k1)
        bucket = pick_bucket(n, self.decode_buckets)
        toks = np.zeros((bucket, k1), np.int32)
        lens = np.zeros((bucket,), np.int32)
        rows = np.full((bucket, self.cfg.pages_per_seq),
                       self.cfg.n_pages, np.int32)
        active = np.zeros((bucket,), bool)
        for i, s in enumerate(seqs):
            toks[i, 0] = pending[i]
            toks[i, 1:] = drafts[i]
            lens[i] = s.length
            rows[i] = s.page_row
            active[i] = True
        _, params = self._live          # one-read snapshot
        self._ck, self._cv, y, counts = self._verify(
            params, self._ck, self._cv, jnp.asarray(toks),
            jnp.asarray(lens), jnp.asarray(rows), jnp.asarray(active))
        y = np.asarray(jax.device_get(y))[:n]
        counts = np.asarray(jax.device_get(counts))[:n]
        for i, s in enumerate(seqs):
            s.length += int(counts[i])
        return y, counts

    def propose(self, seqs: list[_Seq], pending: np.ndarray,
                k: int) -> np.ndarray:
        """Draft role: k greedy proposals per sequence in one program
        call; the proposals' K/V stays in scratch (held on the session
        until :meth:`commit`) — the pool is untouched, so rejected
        drafts never need a ring rollback.  Returns drafts (n, k)."""
        n = len(seqs)
        if not 1 <= n <= self.cfg.max_seqs:
            raise ValueError(f"{n} sequences outside "
                             f"[1, {self.cfg.max_seqs}]")
        if not 1 <= int(k) <= self.window - 1:
            raise ValueError(f"speculate_k {k} outside "
                             f"[1, window-1={self.window - 1}]")
        bucket = pick_bucket(n, self.decode_buckets)
        toks = np.zeros((bucket,), np.int32)
        lens = np.zeros((bucket,), np.int32)
        rows = np.full((bucket, self.cfg.pages_per_seq),
                       self.cfg.n_pages, np.int32)
        active = np.zeros((bucket,), bool)
        for i, s in enumerate(seqs):
            toks[i] = pending[i]
            lens[i] = s.length
            rows[i] = s.page_row
            active[i] = True
        _, params = self._live          # one-read snapshot
        drafts, sk, sv = self._propose(
            params, self._ck, self._cv, jnp.asarray(toks),
            jnp.asarray(lens), jnp.asarray(rows), jnp.asarray(active),
            k=int(k))
        self._scratch = (sk, sv, bucket, n)
        return np.asarray(jax.device_get(drafts))[:n]

    def commit(self, seqs: list[_Seq], counts: np.ndarray) -> None:
        """Draft role: write the accepted prefix of the last
        :meth:`propose` scratch into the pool and advance lengths —
        the draft cache only ever holds accepted history."""
        if self._scratch is None:
            raise RuntimeError("commit() without a pending propose()")
        sk, sv, bucket, n = self._scratch
        self._scratch = None
        if len(seqs) != n:
            raise ValueError(
                f"commit over {len(seqs)} sequences but propose ran "
                f"over {n}")
        self._cow_prepare(seqs, int(sk.shape[2]))
        cnt = np.zeros((bucket,), np.int32)
        lens = np.zeros((bucket,), np.int32)
        rows = np.full((bucket, self.cfg.pages_per_seq),
                       self.cfg.n_pages, np.int32)
        for i, s in enumerate(seqs):
            cnt[i] = counts[i]
            lens[i] = s.length
            rows[i] = s.page_row
        self._ck, self._cv = self._commit(
            self._ck, self._cv, sk, sv, jnp.asarray(lens),
            jnp.asarray(rows), jnp.asarray(cnt))
        for i, s in enumerate(seqs):
            s.length += int(counts[i])

    def release(self, seq: _Seq) -> None:
        self.pool.free_seq(seq.page_row)

    # -- page migration (decode/migrate.py; frontdoor plane) ------------

    def export_pages(self, seq: _Seq) -> tuple[np.ndarray, np.ndarray]:
        """One sequence's KV pages as host arrays, ring layout
        verbatim: ``(n_layers, pages_per_seq, page_size, n_heads,
        d_head)`` per pool — the wire payload of a prefill→decode
        migration.  Read-only (shared/prefix-cache pages export the
        same bytes a local reader would see); call BEFORE release."""
        return self.export_page_ids(seq.page_row)

    def export_page_ids(self, pages) -> tuple[np.ndarray, np.ndarray]:
        """Arbitrary page ids' KV bytes as host arrays — ``(n_layers,
        len(pages), page_size, n_heads, d_head)`` per pool.  The fleet
        prefix-cache ship payload (the authority exports a leased
        entry's pages; a registering replica exports its prompt's
        prefix pages)."""
        rows = jnp.asarray(np.asarray(pages, np.int32).reshape(-1))
        k, v = jax.device_get((self._ck[:, rows], self._cv[:, rows]))
        return np.asarray(k), np.asarray(v)

    def export_pages_batch(self, seqs: list[_Seq]) -> list[tuple]:
        """Every sequence's pages in ONE device transfer (the batched
        prefill server's export leg) — equivalent to per-sequence
        :meth:`export_pages` calls, minus S-1 device round-trips."""
        rows = jnp.asarray(np.stack([s.page_row for s in seqs]))
        k, v = jax.device_get((self._ck[:, rows], self._cv[:, rows]))
        k, v = np.asarray(k), np.asarray(v)
        return [(k[:, i], v[:, i]) for i in range(len(seqs))]

    def adopt_prefix(self, prefix: np.ndarray, k: np.ndarray,
                     v: np.ndarray) -> bool:
        """Adopt fleet-shipped PREFIX pages — ``q`` already-filled
        pages holding a page-aligned prompt prefix — as pure cache
        content (no live sequence).  Arrays are ``(n_layers, q,
        page_size, n_heads, d_head)`` per pool; they are zero-padded
        to the fixed full-row shape so the ONE adopt program serves
        both stream migration and prefix shipping (padding scatters to
        the dropped page id — no new compile).  Returns False, with
        nothing adopted, when sharing is off, the exact prefix is
        already registered, or the pool stays too tight even under
        eviction pressure; the caller treats False as a plain miss."""
        if self.prefix_cache is None:
            return False
        prefix = np.asarray(prefix, np.int32).reshape(-1)
        t = prefix.shape[0]
        ps, pps = self.cfg.page_size, self.cfg.pages_per_seq
        q = t // ps
        if t < ps or t % ps or t > self.window:
            raise ValueError(
                f"adopt_prefix needs a page-aligned prefix of 1..{pps}"
                f" pages, got {t} tokens")
        expect = (self.n_layers, q, ps, self.n_heads, self.cfg.d_head)
        if tuple(k.shape) != expect or tuple(v.shape) != expect:
            raise ValueError(
                f"prefix page arrays {tuple(k.shape)}/{tuple(v.shape)}"
                f" do not match {expect}")
        if self.prefix_cache.contains(prefix):
            return False
        got = self._alloc_pages(q)
        if got is None:
            return False
        shape = (self.n_layers, pps, ps, self.n_heads, self.cfg.d_head)
        kf = np.zeros(shape, self.dtype)
        vf = np.zeros(shape, self.dtype)
        kf[:, :q] = k
        vf[:, :q] = v
        page_row = np.full((pps,), self.cfg.n_pages, np.int32)
        page_row[:q] = got
        try:
            self._ck, self._cv = self._adopt(
                self._ck, self._cv, jnp.asarray(kf), jnp.asarray(vf),
                jnp.asarray(page_row))
        except Exception:
            self.pool.decref(got)
            raise
        self.prefix_cache.insert_pages(prefix, got)
        # the entries hold their own page refs now; dropping the
        # allocation ref makes the pages cache-owned (LRU-evictable)
        self.pool.decref(got)
        return True

    def adopt_pages(self, manifest: dict, k: np.ndarray,
                    v: np.ndarray) -> _Seq:
        """Adopt a migrated sequence: validate the manifest + arrays
        against THIS pool's geometry (typed
        :class:`~theanompi_tpu.decode.migrate.IncompatiblePages` on any
        mismatch — a per-stream refusal, the replica keeps serving),
        allocate a fresh page row, scatter the pages in with the
        fixed-shape adopt program, and register the prompt's prefixes
        in the prefix cache exactly like a local admit."""
        from theanompi_tpu.decode import migrate

        reason = migrate.pages_incompatibility(manifest, k, v, self.cfg)
        if reason is not None:
            raise migrate.IncompatiblePages(reason)
        got = self._alloc_pages(self.cfg.pages_per_seq)
        if got is None:
            raise RuntimeError(
                "adopt_pages() without free pages — the scheduler "
                "must check can_admit() first")
        page_row = np.asarray(got, np.int32)
        try:
            self._ck, self._cv = self._adopt(
                self._ck, self._cv, jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(page_row))
        except Exception:
            # a failed scatter must not leak the sequence's pages
            self.pool.free_seq(page_row)
            raise
        prompt = np.asarray(manifest["prompt"], np.int32)
        if self.prefix_cache is not None:
            self.prefix_cache.insert(prompt, page_row)
        return _Seq(page_row, int(manifest["length"]))

    def reset_cache(self) -> None:
        """Fresh page pool + allocator (restart-from-export path): a
        failed step may have consumed the donated pool buffers, so the
        replica restarts from zeroed pages — live sequences were
        already failed and released by the scheduler."""
        self._ck, self._cv = kvcache.init_pages(self.cfg)
        self.pool = kvcache.PagePool(self.cfg)
        if self.prefix_cache is not None:
            self.prefix_cache = kvcache.PrefixCache(self.pool,
                                                    self.window)
        self._scratch = None

    def warmup(self) -> None:
        """Compile the smallest prefill and decode programs before the
        port binds (the rest compile once at first use — still 'once
        ever' per bucket, which is what the counter pins).  With the
        prefix cache on, the smallest extend program and the COW copy
        program warm too."""
        _, params = self._live
        drop_row = np.full((self.cfg.pages_per_seq,), self.cfg.n_pages,
                           np.int32)
        tokens = np.zeros((1, self.prefill_buckets[0]), np.int32)
        self._ck, self._cv, _ = self._prefill(
            params, self._ck, self._cv, jnp.asarray(tokens),
            jnp.int32(1), jnp.asarray(drop_row))
        bucket = self.decode_buckets[0]
        rows = np.full((bucket, self.cfg.pages_per_seq),
                       self.cfg.n_pages, np.int32)
        self._ck, self._cv, _ = self._decode(
            params, self._ck, self._cv,
            jnp.zeros((bucket,), jnp.int32),
            jnp.zeros((bucket,), jnp.int32), jnp.asarray(rows),
            jnp.zeros((bucket,), bool))
        if self.prefix_cache is not None:
            self._ck, self._cv, _ = self._extend(
                params, self._ck, self._cv, jnp.asarray(tokens),
                jnp.int32(0), jnp.int32(1), jnp.asarray(drop_row))
            self._ck, self._cv = self._copy(
                self._ck, self._cv,
                jnp.zeros((COPY_BUCKET,), jnp.int32),
                jnp.full((COPY_BUCKET,), self.cfg.n_pages, jnp.int32))
        # the adopt scatter (page migration) is one fixed shape — warm
        # it here so a disaggregated replica's first migrated stream
        # never stalls a neighbor's intertoken SLO on a compile
        z = jnp.zeros((self.n_layers, self.cfg.pages_per_seq,
                       self.cfg.page_size, self.n_heads,
                       self.cfg.d_head), self.dtype)
        self._ck, self._cv = self._adopt(self._ck, self._cv, z, z,
                                         jnp.asarray(drop_row))

    def warmup_prefill_batch(self) -> None:
        """Compile the batched prefill program for EVERY (decode
        bucket × prefill bucket) pair up front.  Unlike the serial
        families (whose shapes are per-request and compile-at-first-
        use stays "once ever"), batch OCCUPANCY varies run to run with
        arrival timing — a lazily compiled occupancy bucket would be a
        mid-serving recompile, so the warmup cost buys back the
        zero-steady-state-recompiles pin."""
        _, params = self._live
        for sb in self.decode_buckets:
            if sb < 2:
                # singleton admissions delegate to the serial
                # families (admit_batch) — no (1, token) programs
                continue
            rows = np.full((sb, self.cfg.pages_per_seq),
                           self.cfg.n_pages, np.int32)
            z = jnp.zeros((sb,), jnp.int32)
            for cb in self.prefill_buckets:
                self._ck, self._cv, _ = self._prefill_batch(
                    params, self._ck, self._cv,
                    jnp.zeros((sb, cb), jnp.int32), z, z,
                    jnp.asarray(rows))

    def warmup_spec(self, k: int, role: str) -> None:
        """Compile the speculative programs for the smallest decode
        bucket before the port binds: ``'target'`` warms verify,
        ``'draft'`` warms propose + commit."""
        _, params = self._live
        bucket = self.decode_buckets[0]
        rows = np.full((bucket, self.cfg.pages_per_seq),
                       self.cfg.n_pages, np.int32)
        lens = jnp.zeros((bucket,), jnp.int32)
        if role == "target":
            self._ck, self._cv, _, _ = self._verify(
                params, self._ck, self._cv,
                jnp.zeros((bucket, int(k) + 1), jnp.int32), lens,
                jnp.asarray(rows), jnp.zeros((bucket,), bool))
        elif role == "draft":
            _, sk, sv = self._propose(
                params, self._ck, self._cv,
                jnp.zeros((bucket,), jnp.int32), lens,
                jnp.asarray(rows), jnp.zeros((bucket,), bool),
                k=int(k))
            self._ck, self._cv = self._commit(
                self._ck, self._cv, sk, sv, lens, jnp.asarray(rows),
                jnp.zeros((bucket,), jnp.int32))
        else:
            raise ValueError(f"unknown warmup role {role!r}")
