"""Cached-attention forward over the TRAINING transformer's params.

The decode path shares weights with ``models/transformer.py`` — the
exact ``TransformerLMNet`` param tree an export freezes — but its two
access patterns (full prompt prefill that must EMIT per-layer K/V, and
one-token decode that must READ a paged cache) don't fit the training
module's ``__call__``.  Rather than fork the module, this file applies
the SAME flax submodules (``nn.Dense``/``nn.LayerNorm`` over the
exported subtrees — identical numerics, zero duplicated math) in two
hand-rolled schedules:

* ``full_forward`` — logits + per-layer K/V for a (B, T) prompt, with
  an optional **sliding-window** causal mask (``window`` = the KV
  ring's capacity, decode/kvcache.py).  With ``window=None`` it is the
  training eval path (pinned argmax-identical to ``module.apply`` in
  tests/test_decode.py); with a window it is the oracle for decode
  past an eviction boundary.
* ``decode_block`` / ``embed_tokens`` / ``final_logits`` — the pieces
  the session's one-token decode step composes around the paged
  gather (decode/session.py): attention of one new query against the
  gathered ring plus the token itself.

Quantized exports ride through ``dequantize_tree`` (serving/export.py)
applied INSIDE the jitted fns — int8 weights live on-device at 1/4 the
bytes and rematerialize as f32 per step, or are collapsed once at load
(docs/SERVING.md "Quantized exports").

All functions here are jit-traced (no host syncs — analysis TM301).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from theanompi_tpu.ops.attention import _MASK_NEG, block_scores


def _ln(p, x, dtype):
    return nn.LayerNorm(dtype=dtype).apply({"params": p}, x)


def _dense(p, x, dtype):
    return nn.Dense(p["kernel"].shape[-1], use_bias="bias" in p,
                    dtype=dtype).apply({"params": p}, x)


def embed_tokens(params, tokens, positions):
    """Embedding gather + positional add for arbitrary absolute
    positions (prefill uses 0..T-1; decode uses each sequence's
    current length).  Returns f32 (…, d_model) — the cast to the
    compute dtype happens at the caller, matching the training net."""
    x = jnp.take(params["Embed_0"]["embedding"], tokens, axis=0)
    return x + jnp.take(params["pos_emb"], positions, axis=0)


def final_logits(params, x, dtype):
    """Final LayerNorm + LM head -> f32 logits."""
    h = _ln(params["LayerNorm_0"], x, dtype)
    return _dense(params["Dense_0"], h, dtype).astype(jnp.float32)


def _block_full(bp, x, n_heads: int, dtype, window: int | None):
    """One pre-LN block over a full (B, T, D) sequence; returns the
    block output and the block's K/V (B, T, H, Dh) for the cache."""
    b, t, d = x.shape
    d_head = d // n_heads
    h = _ln(bp["LayerNorm_0"], x, dtype)
    shape = (b, t, n_heads, d_head)
    q = _dense(bp["q_proj"], h, dtype).reshape(shape)
    k = _dense(bp["k_proj"], h, dtype).reshape(shape)
    v = _dense(bp["v_proj"], h, dtype).reshape(shape)
    s = block_scores(q, k, d_head ** -0.5)            # (B, H, T, T) f32
    qi = jnp.arange(t, dtype=jnp.int32)[:, None]
    kj = jnp.arange(t, dtype=jnp.int32)[None, :]
    mask = kj <= qi
    if window is not None:
        mask = mask & (qi - kj < window)
    s = jnp.where(mask[None, None], s, _MASK_NEG)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    x = x + _dense(bp["o_proj"], o.reshape(b, t, d), dtype)
    h2 = _ln(bp["LayerNorm_1"], x, dtype)
    h2 = jax.nn.gelu(_dense(bp["mlp_up"], h2, dtype))
    x = x + _dense(bp["mlp_down"], h2, dtype)
    return x, k, v


def full_forward(params, tokens, n_layers: int, n_heads: int, dtype,
                 window: int | None = None):
    """Whole-prompt forward: (B, T) int tokens -> (f32 logits
    (B, T, V), [k_l], [v_l]) with per-layer K/V (B, T, H, Dh).

    ``window`` bounds attention to the last ``window`` positions per
    query — the KV ring's eviction semantics expressed as a mask, so
    this IS the oracle decode must match across an eviction boundary.
    """
    t = tokens.shape[1]
    x = embed_tokens(params, tokens, jnp.arange(t, dtype=jnp.int32))
    x = x.astype(dtype)
    ks, vs = [], []
    for i in range(n_layers):
        x, k, v = _block_full(params[f"Block_{i}"], x, n_heads, dtype,
                              window)
        ks.append(k)
        vs.append(v)
    return final_logits(params, x, dtype), ks, vs


def chunk_block(bp, x, k_cache, v_cache, ring_mask, n_heads: int,
                dtype, window: int | None = None):
    """One block for a CHUNK of C new tokens per sequence against the
    ring — the multi-token generalization of :func:`decode_block`,
    shared by the speculative VERIFY step (C = k drafted tokens + the
    pending one) and the prefix-cache EXTEND prefill (C = the padded
    prompt suffix).

    ``x``: (S, C, D) the chunk's residual stream, token ``i`` at
    absolute position ``length + i``; ``k_cache``/``v_cache``:
    (S, W, H, Dh) gathered ring (PRE-write); ``ring_mask``:
    (S, C, W) per-query valid-slot mask
    (decode/kvcache.chunk_cache_mask).  Each chunk token attends the
    masked ring PLUS the chunk's earlier tokens and itself (causal
    within the chunk, window-limited when ``window`` is given — their
    K/V are appended as C extra keys, exactly the positions the ring
    does not hold yet).  Returns (x_out (S, C, D),
    k_new (S, C, H, Dh), v_new).
    """
    s_, c, d = x.shape
    d_head = d // n_heads
    h = _ln(bp["LayerNorm_0"], x, dtype)
    shape = (s_, c, n_heads, d_head)
    q = _dense(bp["q_proj"], h, dtype).reshape(shape)
    k_new = _dense(bp["k_proj"], h, dtype).reshape(shape)
    v_new = _dense(bp["v_proj"], h, dtype).reshape(shape)
    scale = d_head ** -0.5
    sc = block_scores(q, k_cache, scale)               # (S, H, C, W)
    sc = jnp.where(ring_mask[:, None], sc, _MASK_NEG)
    self_sc = block_scores(q, k_new, scale)            # (S, H, C, C)
    ci = jnp.arange(c, dtype=jnp.int32)
    cmask = ci[None, :] <= ci[:, None]
    if window is not None:
        cmask = cmask & (ci[:, None] - ci[None, :] < window)
    self_sc = jnp.where(cmask[None, None], self_sc, _MASK_NEG)
    logits = jnp.concatenate([sc, self_sc], axis=-1)   # (S, H, C, W+C)
    p = jax.nn.softmax(logits, axis=-1)
    w = k_cache.shape[1]
    o_cache = jnp.einsum("bhqk,bkhd->bqhd",
                         p[..., :w].astype(v_cache.dtype), v_cache)
    o_self = jnp.einsum("bhqk,bkhd->bqhd",
                        p[..., w:].astype(v_new.dtype), v_new)
    o = (o_cache + o_self).reshape(s_, c, d)
    x = x + _dense(bp["o_proj"], o, dtype)
    h2 = _ln(bp["LayerNorm_1"], x, dtype)
    h2 = jax.nn.gelu(_dense(bp["mlp_up"], h2, dtype))
    x = x + _dense(bp["mlp_down"], h2, dtype)
    return x, k_new, v_new


def decode_block(bp, x, k_cache, v_cache, mask, n_heads: int, dtype):
    """One block for ONE new token per sequence against the ring.

    ``x``: (S, 1, D) the token's residual stream; ``k_cache``/
    ``v_cache``: (S, W, H, Dh) gathered ring (this layer, PRE-write);
    ``mask``: (S, W) valid-slot mask (decode/kvcache.cache_mask — the
    slot this token will overwrite is already excluded).  The token
    attends to the masked ring PLUS itself (its K/V are appended as a
    W+1'th key, exactly the self-attention term the ring does not hold
    yet).  Returns (x_out (S, 1, D), k_new (S, H, Dh), v_new).
    """
    s_, _, d = x.shape
    d_head = d // n_heads
    h = _ln(bp["LayerNorm_0"], x, dtype)
    shape = (s_, 1, n_heads, d_head)
    q = _dense(bp["q_proj"], h, dtype).reshape(shape)
    k_new = _dense(bp["k_proj"], h, dtype).reshape(shape)
    v_new = _dense(bp["v_proj"], h, dtype).reshape(shape)
    scale = d_head ** -0.5
    # scores against the ring: (S, H, 1, W) f32, masked per slot
    sc = block_scores(q, k_cache, scale)
    sc = jnp.where(mask[:, None, None, :], sc, _MASK_NEG)
    # the token's own score: q . k_new -> (S, H, 1, 1)
    self_sc = block_scores(q, k_new, scale)
    logits = jnp.concatenate([sc, self_sc], axis=-1)   # (S, H, 1, W+1)
    p = jax.nn.softmax(logits, axis=-1)
    o_cache = jnp.einsum("bhqk,bkhd->bqhd",
                         p[..., :-1].astype(v_cache.dtype), v_cache)
    o_self = p[..., -1:].transpose(0, 3, 1, 2).astype(v_new.dtype) * v_new
    o = (o_cache + o_self).reshape(s_, 1, d)
    x = x + _dense(bp["o_proj"], o, dtype)
    h2 = _ln(bp["LayerNorm_1"], x, dtype)
    h2 = jax.nn.gelu(_dense(bp["mlp_up"], h2, dtype))
    x = x + _dense(bp["mlp_down"], h2, dtype)
    return x, k_new[:, 0], v_new[:, 0]
