"""theanompi_tpu.decode — autoregressive serving for the transformer
family (docs/SERVING.md "Decode").

The serving subsystem (``theanompi_tpu/serving``) batches fixed-shape
eval requests — right for the CNN zoo, wrong for token generation,
where every request is a loop whose state (the KV cache) must live on
the device between steps.  This package adds that loop:

* ``kvcache``   — paged/ring KV cache: one fixed page pool per
  replica, REFCOUNTED per-sequence page tables (copy-on-write page
  sharing + the cross-request ``PrefixCache``), ring eviction past
  the context window (pure-functional JAX state);
* ``model``     — cached-attention forward sharing weights with the
  training ``Block`` (the exported ``TransformerLMNet`` params,
  applied through the same flax submodules);
* ``session``   — ``DecodeSession``: prefill/decode bucket split with
  cache-buffer donation; steady state never recompiles (compile-
  counter pinned);
* ``scheduler`` — ``ContinuousBatcher``: iteration-level scheduling —
  admit/evict sequences BETWEEN decode steps — plus ``DecodeReplica``,
  the restart-from-export wrapper the inference server pools.

Wire surface: the inference server's ``generate`` op
(``InferenceClient.generate``), served by ``tmlocal SERVE --decode``.

    # exporter side (a trained TransformerLM)
    from theanompi_tpu.serving import export_model
    export_model(model, "exports/lm", weight_dtype="bf16")

    # server:  tmlocal SERVE --export-dir exports/lm --decode
    # client
    from theanompi_tpu.serving import InferenceClient
    tokens = InferenceClient("host:45900").generate(prompt, max_new=64)
"""

from theanompi_tpu.decode.kvcache import (
    CacheConfig,
    PagePool,
    PrefixCache,
)
from theanompi_tpu.decode.migrate import (
    IncompatiblePages,
    page_manifest,
)
from theanompi_tpu.decode.model import full_forward
from theanompi_tpu.decode.scheduler import (
    ContinuousBatcher,
    DecodePolicy,
    DecodeReplica,
)
from theanompi_tpu.decode.session import (
    DecodeSession,
    default_prefill_buckets,
)

__all__ = [
    "CacheConfig", "PagePool", "PrefixCache", "full_forward",
    "ContinuousBatcher", "DecodePolicy", "DecodeReplica",
    "DecodeSession", "IncompatiblePages", "default_prefill_buckets",
    "page_manifest",
]
