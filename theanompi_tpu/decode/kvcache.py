"""Paged/ring KV-cache — pure-functional JAX state + a host-side pool.

The decode subsystem's device state is ONE fixed page pool per replica
(``k_pages``/``v_pages``: ``(layers, n_pages, page_size, heads,
d_head)``), never resized and never reshaped: every jitted decode step
sees the same array shapes regardless of which sequences are live, so
a replica compiles each (prefill bucket, decode bucket) program ONCE
and steady-state serving never recompiles — the inference-side twin of
the training stack's bucket discipline (serving/batcher.py).

Sequences own pages through a **page table** (``pages_per_seq`` page
ids per live sequence, allocated from the pool's free list on
admission, returned on eviction), so a sequence's KV bytes are
scattered wherever free pages were — admission cost is O(pages), not
a copy.  Within its pages a sequence is a **ring** over
``window = pages_per_seq * page_size`` token slots: token at absolute
position ``p`` lives in slot ``p % window``, and once ``p >= window``
the write lands on the slot of token ``p - window`` — eviction past
the context window is free, it is the ring wrapping.  Attention
therefore covers exactly the last ``window`` tokens; the full-forward
oracle for a decode past the boundary is the SAME model with a
sliding-window causal mask (decode/model.py ``full_forward``), which
tests pin token-identical (tests/test_decode.py).

Everything here is either pure math safe inside ``jax.jit``
(gather/scatter/mask helpers — no host syncs) or host-side allocator
state owned by ONE scheduler thread (``PagePool`` — no locks by
design; decode/scheduler.py is the single caller).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Shape contract of one replica's page pool."""

    n_layers: int
    n_heads: int
    d_head: int
    #: tokens per page (the allocation granule)
    page_size: int = 16
    #: pages per live sequence — fixes the ring window
    pages_per_seq: int = 8
    #: max concurrently-live sequences (the decode batch ceiling)
    max_seqs: int = 8
    #: KV storage dtype (the model's compute dtype)
    dtype: str = "float32"

    def __post_init__(self):
        for f in ("n_layers", "n_heads", "d_head", "page_size",
                  "pages_per_seq", "max_seqs"):
            if int(getattr(self, f)) < 1:
                raise ValueError(f"CacheConfig.{f} must be >= 1")

    @property
    def window(self) -> int:
        """Ring capacity in tokens = the attention context window."""
        return self.page_size * self.pages_per_seq

    @property
    def n_pages(self) -> int:
        """Pool size: every slot's worth of sequences can hold a full
        ring (admission can only fail on max_seqs, never on pages)."""
        return self.max_seqs * self.pages_per_seq


def init_pages(cfg: CacheConfig):
    """The replica's page pool, zeros: ``(k_pages, v_pages)`` of shape
    ``(n_layers, n_pages, page_size, n_heads, d_head)``."""
    shape = (cfg.n_layers, cfg.n_pages, cfg.page_size, cfg.n_heads,
             cfg.d_head)
    dt = jnp.dtype(cfg.dtype)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


# ---------------------------------------------------------------------------
# Ring math (pure; used on host by tests and inside jit by the session)
# ---------------------------------------------------------------------------


def stored_positions(lengths, window: int):
    """Absolute token position held by each ring slot.

    ``lengths``: (S,) tokens written so far per sequence.  Slot ``j``
    of a ring holds the LARGEST position ``p < length`` with
    ``p % window == j`` — i.e. ``p_j = (length-1) - ((length-1-j) mod
    window)``; a slot no position has reached yet comes out negative.
    Returns (S, window) int32.
    """
    j = jnp.arange(window, dtype=jnp.int32)[None, :]
    last = lengths.astype(jnp.int32)[:, None] - 1
    return last - jnp.mod(last - j, window)


def cache_mask(lengths, window: int):
    """(S, window) bool: ring slots holding a position the NEXT token
    (at position ``length``) may attend — written (``p >= 0``) and
    inside the sliding window (``p > length - window``; the slot the
    new token is about to overwrite holds ``length - window`` and is
    correctly excluded)."""
    pos = stored_positions(lengths, window)
    lens = lengths.astype(jnp.int32)[:, None]
    return (pos >= 0) & (pos > lens - window)


def ring_from_prompt(kv, length, window: int):
    """Scatter one prompt's per-position K or V into its ring layout.

    ``kv``: (T_pad, heads, d_head) for one sequence, position ``p`` at
    row ``p``; ``length``: the real prompt length (<= T_pad).  Only the
    last ``min(length, window)`` positions survive (the rest are
    already evicted); each lands in slot ``p % window`` — at most one
    surviving position per slot, so the scatter has no duplicate
    indices.  Pad rows scatter to index ``window`` and are dropped.
    Returns (window, heads, d_head).
    """
    t_pad = kv.shape[0]
    pos = jnp.arange(t_pad, dtype=jnp.int32)
    length = jnp.asarray(length, jnp.int32)
    valid = (pos < length) & (pos >= length - window)
    slots = jnp.where(valid, jnp.mod(pos, window), window)
    ring = jnp.zeros((window, *kv.shape[1:]), kv.dtype)
    return ring.at[slots].set(kv, mode="drop")


# ---------------------------------------------------------------------------
# Page gather/scatter (pure; inside jit)
# ---------------------------------------------------------------------------


def gather_layer(pages, page_rows):
    """One layer's cached KV per sequence, ring-ordered.

    ``pages``: (n_pages, page_size, H, D) — ONE layer of the pool;
    ``page_rows``: (S, pages_per_seq) page ids.  Returns
    (S, window, H, D): slot ``j`` is page ``j // page_size`` offset
    ``j % page_size``.
    """
    s, pps = page_rows.shape
    g = pages[page_rows]                     # (S, pps, page_size, H, D)
    return g.reshape(s, pps * pages.shape[1], *pages.shape[2:])


def write_token_all(pages, page_rows, lengths, active, kv):
    """Write each sequence's NEW token (position ``length``) into the
    pool at ring slot ``length % window``, all layers in one scatter.

    ``pages``: the full pool (L, n_pages, page_size, H, D); ``kv``:
    (L, S, H, D) — each layer's new-token K or V.  Slot/page math is
    shared across layers (same sequences), so the write is one batched
    ``.at[:, page, off].set``; inactive (bucket-padding) rows are
    routed to page id ``n_pages`` and dropped by the scatter, so
    padding can never clobber a live page.
    """
    page_size = pages.shape[2]
    window = page_rows.shape[1] * page_size
    slot = jnp.mod(lengths.astype(jnp.int32), window)
    page = jnp.take_along_axis(page_rows,
                               (slot // page_size)[:, None], axis=1)[:, 0]
    page = jnp.where(active, page, pages.shape[1])
    off = jnp.mod(slot, page_size)
    return pages.at[:, page, off].set(kv, mode="drop")


# ---------------------------------------------------------------------------
# Host-side allocator
# ---------------------------------------------------------------------------


class PagePool:
    """Free-list page allocator for one replica's pool.

    Owned by the replica's single scheduler thread
    (decode/scheduler.py) — not thread-safe by design, the same
    single-owner discipline as the session's host-side sequence state.
    """

    def __init__(self, cfg: CacheConfig):
        self.cfg = cfg
        self._free = list(range(cfg.n_pages - 1, -1, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_fraction(self) -> float:
        return 1.0 - len(self._free) / self.cfg.n_pages

    def alloc_seq(self) -> np.ndarray | None:
        """One sequence's page row (``pages_per_seq`` ids), or None
        when the pool cannot cover it."""
        n = self.cfg.pages_per_seq
        if len(self._free) < n:
            return None
        ids = [self._free.pop() for _ in range(n)]
        return np.asarray(ids, np.int32)

    def free_seq(self, page_row: np.ndarray) -> None:
        for p in page_row.tolist():
            if not 0 <= p < self.cfg.n_pages:
                raise ValueError(f"freeing foreign page id {p}")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
            self._free.append(p)
