"""Paged/ring KV-cache — pure-functional JAX state + a host-side pool.

Pages are REFCOUNTED: a per-sequence page table may alias pages owned
by other sequences or by the cross-request :class:`PrefixCache` (a
shared system prompt prefills once and is mapped read-only into every
stream that starts with it).  Writes stay sound through host-side
copy-on-write — before any jitted step writes a slot, the session
replaces every page it touches whose refcount is > 1 with a private
device copy (``DecodeSession._cow_prepare``), so the device arrays
themselves never need to know about sharing.

The decode subsystem's device state is ONE fixed page pool per replica
(``k_pages``/``v_pages``: ``(layers, n_pages, page_size, heads,
d_head)``), never resized and never reshaped: every jitted decode step
sees the same array shapes regardless of which sequences are live, so
a replica compiles each (prefill bucket, decode bucket) program ONCE
and steady-state serving never recompiles — the inference-side twin of
the training stack's bucket discipline (serving/batcher.py).

Sequences own pages through a **page table** (``pages_per_seq`` page
ids per live sequence, allocated from the pool's free list on
admission, returned on eviction), so a sequence's KV bytes are
scattered wherever free pages were — admission cost is O(pages), not
a copy.  Within its pages a sequence is a **ring** over
``window = pages_per_seq * page_size`` token slots: token at absolute
position ``p`` lives in slot ``p % window``, and once ``p >= window``
the write lands on the slot of token ``p - window`` — eviction past
the context window is free, it is the ring wrapping.  Attention
therefore covers exactly the last ``window`` tokens; the full-forward
oracle for a decode past the boundary is the SAME model with a
sliding-window causal mask (decode/model.py ``full_forward``), which
tests pin token-identical (tests/test_decode.py).

Everything here is either pure math safe inside ``jax.jit``
(gather/scatter/mask helpers — no host syncs) or host-side allocator
state owned by ONE scheduler thread (``PagePool`` — no locks by
design; decode/scheduler.py is the single caller).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Shape contract of one replica's page pool."""

    n_layers: int
    n_heads: int
    d_head: int
    #: tokens per page (the allocation granule)
    page_size: int = 16
    #: pages per live sequence — fixes the ring window
    pages_per_seq: int = 8
    #: max concurrently-live sequences (the decode batch ceiling)
    max_seqs: int = 8
    #: KV storage dtype (the model's compute dtype)
    dtype: str = "float32"

    def __post_init__(self):
        for f in ("n_layers", "n_heads", "d_head", "page_size",
                  "pages_per_seq", "max_seqs"):
            if int(getattr(self, f)) < 1:
                raise ValueError(f"CacheConfig.{f} must be >= 1")

    @property
    def window(self) -> int:
        """Ring capacity in tokens = the attention context window."""
        return self.page_size * self.pages_per_seq

    @property
    def n_pages(self) -> int:
        """Pool size: every slot's worth of sequences can hold a full
        ring (admission can only fail on max_seqs, never on pages)."""
        return self.max_seqs * self.pages_per_seq


def init_pages(cfg: CacheConfig):
    """The replica's page pool, zeros: ``(k_pages, v_pages)`` of shape
    ``(n_layers, n_pages, page_size, n_heads, d_head)``."""
    shape = (cfg.n_layers, cfg.n_pages, cfg.page_size, cfg.n_heads,
             cfg.d_head)
    dt = jnp.dtype(cfg.dtype)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


# ---------------------------------------------------------------------------
# Ring math (pure; used on host by tests and inside jit by the session)
# ---------------------------------------------------------------------------


def stored_positions(lengths, window: int):
    """Absolute token position held by each ring slot.

    ``lengths``: (S,) tokens written so far per sequence.  Slot ``j``
    of a ring holds the LARGEST position ``p < length`` with
    ``p % window == j`` — i.e. ``p_j = (length-1) - ((length-1-j) mod
    window)``; a slot no position has reached yet comes out negative.
    Returns (S, window) int32.
    """
    j = jnp.arange(window, dtype=jnp.int32)[None, :]
    last = lengths.astype(jnp.int32)[:, None] - 1
    return last - jnp.mod(last - j, window)


def cache_mask(lengths, window: int):
    """(S, window) bool: ring slots holding a position the NEXT token
    (at position ``length``) may attend — written (``p >= 0``) and
    inside the sliding window (``p > length - window``; the slot the
    new token is about to overwrite holds ``length - window`` and is
    correctly excluded)."""
    pos = stored_positions(lengths, window)
    lens = lengths.astype(jnp.int32)[:, None]
    return (pos >= 0) & (pos > lens - window)


def chunk_cache_mask(lengths, chunk: int, window: int):
    """(S, chunk, window) bool: ring slots the chunk's ``i``-th new
    token (absolute position ``length + i``) may attend — the
    per-query generalization of :func:`cache_mask` for the multi-token
    verify/extend programs.  Slot contents are PRE-write (the chunk's
    own tokens attend each other inside the chunk, not via the ring),
    so the stored position per slot is computed from ``lengths``
    alone; each query just tightens the sliding window by its own
    offset."""
    pos = stored_positions(lengths, window)            # (S, W)
    qpos = (lengths.astype(jnp.int32)[:, None]
            + jnp.arange(chunk, dtype=jnp.int32)[None, :])  # (S, C)
    return ((pos[:, None, :] >= 0)
            & (pos[:, None, :] > qpos[:, :, None] - window))


def ring_from_prompt(kv, length, window: int):
    """Scatter one prompt's per-position K or V into its ring layout.

    ``kv``: (T_pad, heads, d_head) for one sequence, position ``p`` at
    row ``p``; ``length``: the real prompt length (<= T_pad).  Only the
    last ``min(length, window)`` positions survive (the rest are
    already evicted); each lands in slot ``p % window`` — at most one
    surviving position per slot, so the scatter has no duplicate
    indices.  Pad rows scatter to index ``window`` and are dropped.
    Returns (window, heads, d_head).
    """
    t_pad = kv.shape[0]
    pos = jnp.arange(t_pad, dtype=jnp.int32)
    length = jnp.asarray(length, jnp.int32)
    valid = (pos < length) & (pos >= length - window)
    slots = jnp.where(valid, jnp.mod(pos, window), window)
    ring = jnp.zeros((window, *kv.shape[1:]), kv.dtype)
    return ring.at[slots].set(kv, mode="drop")


# ---------------------------------------------------------------------------
# Page gather/scatter (pure; inside jit)
# ---------------------------------------------------------------------------


def gather_layer(pages, page_rows):
    """One layer's cached KV per sequence, ring-ordered.

    ``pages``: (n_pages, page_size, H, D) — ONE layer of the pool;
    ``page_rows``: (S, pages_per_seq) page ids.  Returns
    (S, window, H, D): slot ``j`` is page ``j // page_size`` offset
    ``j % page_size``.
    """
    s, pps = page_rows.shape
    g = pages[page_rows]                     # (S, pps, page_size, H, D)
    return g.reshape(s, pps * pages.shape[1], *pages.shape[2:])


def write_tokens_all(pages, page_rows, lengths, counts, kv):
    """Write the first ``counts[s]`` of C new tokens per sequence
    (positions ``length .. length+counts-1``) into the pool, all
    layers in one scatter.

    ``pages``: the full pool (L, n_pages, page_size, H, D); ``kv``:
    (L, S, C, H, D) — each layer's per-chunk-token K or V; ``counts``:
    (S,) int — how many leading chunk tokens are actually written (the
    speculative ACCEPT count, or the real suffix length of a padded
    extend-prefill chunk; 0 for an inactive bucket-padding row).
    Slot/page math is shared across layers, so the write is one
    batched ``.at[:, page, off].set``; tokens past a sequence's count
    are routed to page id ``n_pages`` and dropped by the scatter —
    which is exactly how REJECTED draft tokens never reach the cache
    (no rollback needed: nothing was written).  Tokens more than
    ``window`` positions BEHIND a sequence's count are dropped the
    same way (they are already evicted — the ring wrapped past them),
    so a count may exceed the window: only the last ``window``
    positions survive, each on a distinct slot — the batched cold
    prefill of a window-exceeding prompt is ``ring_from_prompt``'s
    ``p >= length - window`` filter expressed per row.
    """
    page_size = pages.shape[2]
    window = page_rows.shape[1] * page_size
    c = kv.shape[2]
    i = jnp.arange(c, dtype=jnp.int32)[None, :]                  # (1, C)
    pos = lengths.astype(jnp.int32)[:, None] + i                 # (S, C)
    slot = jnp.mod(pos, window)
    page = jnp.take_along_axis(page_rows, slot // page_size, axis=1)
    cnt = counts.astype(jnp.int32)[:, None]
    page = jnp.where((i < cnt) & (i >= cnt - window), page,
                     pages.shape[1])
    off = jnp.mod(slot, page_size)
    return pages.at[:, page, off].set(kv, mode="drop")


def write_token_all(pages, page_rows, lengths, active, kv):
    """Write each sequence's NEW token (position ``length``) into the
    pool at ring slot ``length % window`` — the one-token decode step,
    expressed as a chunk of 1 (:func:`write_tokens_all`); inactive
    (bucket-padding) rows write nothing."""
    counts = jnp.where(active, 1, 0)
    return write_tokens_all(pages, page_rows, lengths, counts,
                            kv[:, :, None])


# ---------------------------------------------------------------------------
# Host-side allocator
# ---------------------------------------------------------------------------


class PagePool:
    """Refcounted free-list page allocator for one replica's pool.

    Owned by the replica's single scheduler thread
    (decode/scheduler.py) — not thread-safe by design, the same
    single-owner discipline as the session's host-side sequence state.

    A page's refcount counts every page-table slot and every
    :class:`PrefixCache` entry holding it; a page returns to the free
    list only when the LAST reference drops, which is what lets a
    shared prefix page outlive the sequence that prefilled it.  A
    refcount > 1 marks the page read-only for writers — the session's
    copy-on-write check (``DecodeSession._cow_prepare``).
    """

    def __init__(self, cfg: CacheConfig):
        self.cfg = cfg
        self._free = list(range(cfg.n_pages - 1, -1, -1))
        self._refs = np.zeros(cfg.n_pages, np.int32)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_fraction(self) -> float:
        return 1.0 - len(self._free) / self.cfg.n_pages

    def refcount(self, page: int) -> int:
        return int(self._refs[page])

    def alloc(self, n: int) -> list[int] | None:
        """``n`` fresh pages at refcount 1 each, or None when the free
        list cannot cover it (the caller may relieve pressure by
        evicting PrefixCache entries and retry)."""
        if len(self._free) < n:
            return None
        ids = [self._free.pop() for _ in range(n)]
        self._refs[ids] = 1
        return ids

    def alloc_seq(self) -> np.ndarray | None:
        """One sequence's page row (``pages_per_seq`` ids), or None
        when the pool cannot cover it."""
        ids = self.alloc(self.cfg.pages_per_seq)
        return None if ids is None else np.asarray(ids, np.int32)

    def incref(self, pages) -> None:
        """Adopt already-allocated pages (a prefix-cache hit aliasing
        shared pages into a new sequence's table, or a cache entry
        registering a prefill's pages)."""
        for p in np.asarray(pages, np.int64).reshape(-1).tolist():
            if not 0 <= p < self.cfg.n_pages:
                raise ValueError(f"incref of foreign page id {p}")
            if self._refs[p] < 1:
                raise ValueError(f"incref of free page {p}")
            self._refs[p] += 1

    def decref(self, pages) -> int:
        """Drop one reference per page; pages reaching zero return to
        the free list.  Returns how many pages were freed."""
        freed = 0
        for p in np.asarray(pages, np.int64).reshape(-1).tolist():
            if not 0 <= p < self.cfg.n_pages:
                raise ValueError(f"freeing foreign page id {p}")
            if self._refs[p] < 1:
                raise ValueError(f"double free of page {p}")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)
                freed += 1
        return freed

    def free_seq(self, page_row: np.ndarray) -> None:
        self.decref(page_row)


class _PrefixEntry:
    __slots__ = ("pages", "n_tokens")

    def __init__(self, pages: list[int], n_tokens: int):
        self.pages = pages
        self.n_tokens = n_tokens


class PrefixCache:
    """Cross-request prefix cache (scheduler-thread owned, like the
    pool it feeds).

    Maps the BYTES of a page-aligned prompt prefix to the page ids
    holding those positions' K/V — exact-match keys, so a hash
    collision can never alias two different prompts.  An admit whose
    prompt starts with a cached prefix aliases the shared pages into
    its page table (``PagePool.incref``) and prefills only the
    suffix; the first write that would land on a shared page triggers
    copy-on-write.  Entries hold their own refcount on every page, so
    a shared prefix outlives the sequence that prefilled it; eviction
    is LRU under allocation pressure (``evict_lru``) and rides the
    pool's free-list discipline — a page only truly frees when no
    live sequence aliases it either.

    Sharing is sound only while slot == position (one un-wrapped ring
    lap): prompts longer than the window prefill through eviction and
    are neither cached nor matched.
    """

    def __init__(self, pool: PagePool, window: int):
        self.pool = pool
        self.window = int(window)
        self.page_size = int(pool.cfg.page_size)
        #: insertion-ordered = LRU order (move_to_end on hit)
        self._entries: dict[bytes, _PrefixEntry] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def cached_pages(self) -> int:
        """Distinct pages referenced by at least one entry."""
        return len({p for e in self._entries.values() for p in e.pages})

    def evictable_pages(self) -> int:
        """Pages that would return to the free list if every entry
        were evicted — pages ONLY the cache still holds (refcount ==
        the number of entries referencing them)."""
        held: dict[int, int] = {}
        for e in self._entries.values():
            for p in e.pages:
                held[p] = held.get(p, 0) + 1
        return sum(1 for p, n in held.items()
                   if self.pool.refcount(p) == n)

    def _max_pages(self, prompt_len: int) -> int:
        """Longest page-aligned PROPER prefix (>= 1 suffix token must
        remain: its logits seed the first generated token) that fits
        one ring lap."""
        if prompt_len > self.window:
            return 0
        return (prompt_len - 1) // self.page_size

    def lookup(self, prompt: np.ndarray) -> _PrefixEntry | None:
        """Longest cached page-aligned proper prefix of ``prompt``
        (MRU-bumped), or None.  The caller adopts the entry's pages
        with ``PagePool.incref`` BEFORE any allocation that could
        trigger eviction."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        for q in range(self._max_pages(prompt.shape[0]), 0, -1):
            key = prompt[:q * self.page_size].tobytes()
            e = self._entries.get(key)
            if e is not None:
                self._entries.pop(key)
                self._entries[key] = e      # move to MRU
                self.hits += 1
                return e
        self.misses += 1
        return None

    def insert(self, prompt: np.ndarray, page_row: np.ndarray) -> int:
        """Register every page-aligned proper prefix of a just-
        prefilled prompt (nested entries make partial-overlap hits
        possible); each entry increfs the pages it references.
        Returns the number of entries added."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        added = 0
        for q in range(1, self._max_pages(prompt.shape[0]) + 1):
            key = prompt[:q * self.page_size].tobytes()
            if key in self._entries:
                continue
            pages = [int(p) for p in page_row[:q]]
            self.pool.incref(pages)
            self._entries[key] = _PrefixEntry(pages, q * self.page_size)
            added += 1
        return added

    def contains(self, prefix: np.ndarray) -> bool:
        """Exact-key membership probe (no LRU bump, no hit/miss
        accounting) — the fleet-cache authority's "already
        registered?" check before adopting shipped pages."""
        prefix = np.asarray(prefix, np.int32).reshape(-1)
        return prefix.tobytes() in self._entries

    def insert_pages(self, prefix: np.ndarray, pages) -> int:
        """Register a page-aligned prefix whose OWN pages are given
        explicitly — including the exact full length.  Unlike
        :meth:`insert` (which registers only PROPER prefixes of a live
        prompt, because the suffix token's logits must come from a
        prefill), a fleet-shipped prefix is pure cache content with no
        live sequence behind it, so its full length is a legal key.
        Every nested page-aligned sub-prefix registers too; each entry
        increfs the pages it references.  Returns entries added."""
        prefix = np.asarray(prefix, np.int32).reshape(-1)
        n = prefix.shape[0]
        ps = self.page_size
        if n < ps or n % ps or n > self.window:
            raise ValueError(
                f"insert_pages needs a page-aligned prefix of 1.."
                f"{self.window // ps} pages, got {n} tokens")
        pages = [int(p)
                 for p in np.asarray(pages, np.int64).reshape(-1)]
        if len(pages) != n // ps:
            raise ValueError(
                f"{len(pages)} pages cannot hold {n} tokens at "
                f"page_size {ps}")
        added = 0
        for q in range(1, n // ps + 1):
            key = prefix[:q * ps].tobytes()
            if key in self._entries:
                continue
            self.pool.incref(pages[:q])
            self._entries[key] = _PrefixEntry(list(pages[:q]), q * ps)
            added += 1
        return added

    def evict_lru(self) -> int:
        """Drop the least-recently-used entry; returns pages actually
        freed (0 both when the cache is empty and when every page is
        still aliased by a live sequence or a longer entry)."""
        if not self._entries:
            return 0
        key = next(iter(self._entries))
        e = self._entries.pop(key)
        self.evictions += 1
        return self.pool.decref(e.pages)

    def evict_all(self) -> int:
        freed = 0
        while self._entries:
            freed += self.evict_lru()
        return freed
