"""ContinuousBatcher — iteration-level scheduling for token decode.

``serving/batcher.py`` coalesces REQUESTS: a batch forms, runs once,
and every member completes together.  Token generation breaks that
shape — sequences finish at different lengths, and a per-request batch
would hold 1-token stragglers hostage to 64-token neighbors.  This
scheduler batches ITERATIONS instead (the continuous-batching
discipline): between any two decode steps it may **admit** pending
prompts into free cache slots and **evict** finished sequences, so a
request admitted mid-stream shares its very first decode step with
whatever is already in flight (pinned by tests/test_decode.py and the
preflight decode smoke) and an evicted slot is refilled without
draining the batch.

What carries over from ``DynamicBatcher`` unchanged:

* **typed O(1) admission** — a full pending queue raises
  :class:`~theanompi_tpu.serving.batcher.Overloaded` immediately (the
  same class, so it rides the wire's ``err`` prefix identically);
* **deadline-from-oldest** — here the oldest pending prompt's wait is
  bounded by ONE decode step + its prefill, because admission runs
  every iteration rather than at batch boundaries;
* the **dead-replica contract** — a step failure hands the exception
  to ``on_error``; a falsy return marks the batcher dead, pending and
  future submits get ``Overloaded``, and the server routes around the
  corpse (``DecodeReplica`` owns restart-from-export, exactly like
  ``Replica``).

Telemetry: per-token inter-token latency (``decode/intertoken_ms`` —
the serving SLO, not request latency), tokens/steps counters, active/
pending gauges, cache occupancy and evictions — all in the monitor
registry (docs/OBSERVABILITY.md) plus a host-side p50/p99 ring in
``stats()`` for the bench tools.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_condition, make_lock
from theanompi_tpu.resilience import faults
from theanompi_tpu.serving.batcher import Overloaded


@dataclasses.dataclass(frozen=True)
class DecodePolicy:
    """Admission/generation knobs for one decode replica."""

    #: admission bound: pending PROMPTS beyond this are rejected with
    #: Overloaded instead of queued (docs/SERVING.md overload
    #: semantics)
    max_pending: int = 32
    #: server-side cap on tokens generated per request
    max_new_cap: int = 256
    #: a blocked generate() gives up after this long
    submit_timeout_s: float = 120.0
    #: greedy decode stops early on this token (None = length-only)
    eos_token: int | None = None


class _GenRequest:
    __slots__ = ("prompt", "max_new", "out", "done", "error", "t0",
                 "t_last", "cancelled")

    def __init__(self, prompt: np.ndarray, max_new: int):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.out: list[int] = []
        self.done = threading.Event()
        self.error: BaseException | None = None
        self.t0 = time.monotonic()
        self.t_last = self.t0
        #: set by an abandoning client thread, read by the scheduler at
        #: the next step boundary — a benign boolean race (either the
        #: scheduler sees it this step or the next)
        self.cancelled = False


class ContinuousBatcher:
    """One decode replica's scheduler thread + admission queue.

    ``session`` is a :class:`~theanompi_tpu.decode.session.DecodeSession`;
    its cache state is owned by THIS object's single scheduler thread.
    ``generate`` is the client-side entry (any thread)."""

    def __init__(self, session, policy: DecodePolicy | None = None,
                 replica: int = 0, on_error=None):
        self.session = session
        self.policy = policy or DecodePolicy()
        self.replica = int(replica)
        self._on_error = on_error
        self._pending: deque[_GenRequest] = deque()  # guarded_by: self._lock
        self._lock = make_lock("ContinuousBatcher._lock")
        self._cond = make_condition(self._lock)
        self._dead = False                           # guarded_by: self._lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # scheduler-thread-owned live set: (request, session _Seq)
        self._active: list[tuple[_GenRequest, object]] = []
        self._steps = 0
        # plain-int stats (torn reads of monotonic ints are harmless
        # for stats(), the DynamicBatcher convention)
        self.n_tokens = 0
        self.n_steps = 0
        self.n_admitted = 0
        self.n_evicted = 0
        self.n_overloaded = 0
        self.n_step_errors = 0
        #: steps whose decode batch held >= 2 sequences — the
        #: iteration-level-sharing proof the preflight smoke asserts
        self.shared_steps = 0
        self.max_concurrent = 0
        self._intertoken_ms: deque[float] = deque(maxlen=4096)  # guarded_by: self._lock

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "ContinuousBatcher":
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"decode-scheduler-{self.replica}")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        self._fail_pending(Overloaded(
            f"decode replica {self.replica} is shutting down"))

    @property
    def alive(self) -> bool:
        with self._lock:
            return not self._dead and not self._stop.is_set()

    def stats(self) -> dict:
        with self._lock:
            pending = len(self._pending)
            lat = (np.sort(np.asarray(self._intertoken_ms, np.float64))
                   if self._intertoken_ms else np.zeros((0,)))
        pick = (lambda q: float(lat[min(len(lat) - 1, int(q * len(lat)))])
                if len(lat) else None)
        return {
            "replica": self.replica,
            "alive": self.alive,
            "tokens": self.n_tokens,
            "steps": self.n_steps,
            "admitted": self.n_admitted,
            "evicted": self.n_evicted,
            "overloaded": self.n_overloaded,
            "step_errors": self.n_step_errors,
            "shared_steps": self.shared_steps,
            "max_concurrent": self.max_concurrent,
            "active": len(self._active),
            "pending": pending,
            "free_pages": self.session.pool.free_pages,
            "intertoken_ms": {"p50": pick(0.50), "p99": pick(0.99),
                              "count": len(lat)},
            "compiles": dict(self.session.compiles),
        }

    # -- client side ----------------------------------------------------

    def generate(self, prompt, max_new: int | None = None) -> list[int]:
        """Greedy-decode up to ``max_new`` tokens after ``prompt``;
        blocks until the sequence finishes.  Raises
        :class:`Overloaded` on admission rejection or re-raises the
        step error that consumed this request."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        max_new = int(max_new if max_new is not None
                      else self.policy.max_new_cap)
        max_new = min(max_new, self.policy.max_new_cap)
        if prompt.shape[0] < 1 or max_new < 1:
            raise ValueError("need a non-empty prompt and max_new >= 1")
        if prompt.shape[0] > self.session.max_prompt:
            raise ValueError(
                f"prompt length {prompt.shape[0]} exceeds the largest "
                f"prefill bucket {self.session.max_prompt}")
        if prompt.shape[0] + max_new > self.session.max_len:
            raise ValueError(
                f"prompt+max_new {prompt.shape[0] + max_new} exceeds "
                f"the model's max_len {self.session.max_len} "
                "(positional table)")
        req = _GenRequest(prompt, max_new)
        with self._cond:
            if self._dead or self._stop.is_set():
                self.n_overloaded += 1
                monitor.inc("decode/overloaded_total",
                            replica=self.replica)
                raise Overloaded(
                    f"decode replica {self.replica} is not serving")
            if len(self._pending) >= self.policy.max_pending:
                self.n_overloaded += 1
                monitor.inc("decode/overloaded_total",
                            replica=self.replica)
                raise Overloaded(
                    f"decode replica {self.replica} admission queue is "
                    f"full ({self.policy.max_pending} pending); "
                    "rejecting instead of queueing unboundedly")
            self._pending.append(req)
            monitor.set_gauge("decode/pending", len(self._pending),
                              replica=self.replica)
            self._cond.notify_all()
        if not req.done.wait(self.policy.submit_timeout_s):
            with self._cond:
                try:
                    self._pending.remove(req)
                except ValueError:
                    # already admitted: the scheduler evicts it at the
                    # next step boundary via the cancelled flag
                    req.cancelled = True
            raise TimeoutError(
                f"generate timed out after "
                f"{self.policy.submit_timeout_s}s on decode replica "
                f"{self.replica}")
        if req.error is not None:
            raise req.error
        return req.out

    # -- scheduler thread ----------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._admit()
            if not self._active:
                with self._cond:
                    if not self._pending and not self._stop.is_set():
                        self._cond.wait(0.25)
                        monitor.set_gauge("serving/replica_heartbeat",
                                          time.time(),
                                          replica=self.replica)
                continue
            self._step()
        self._drain()

    def _take_pending(self) -> _GenRequest | None:
        with self._cond:
            req = self._pending.popleft() if self._pending else None
            monitor.set_gauge("decode/pending", len(self._pending),
                              replica=self.replica)
            return req

    def _admit(self) -> None:
        """Admit pending prompts into free slots — every iteration, so
        the oldest waiter's deadline is one decode step away."""
        while (len(self._active) < self.session.cfg.max_seqs
                and self.session.can_admit()
                and not self._stop.is_set()):
            req = self._take_pending()
            if req is None:
                return
            if req.cancelled:
                continue
            t0 = time.monotonic()
            try:
                seq, logits = self.session.admit(req.prompt)
            except Exception as e:
                if isinstance(e, ValueError):
                    # a bad request must not kill the replica
                    self._fail_requests([req], e)
                    continue
                self._abort_inflight(e, extra=[req])
                return
            monitor.observe("decode/prefill_ms",
                            (time.monotonic() - t0) * 1e3,
                            replica=self.replica)
            self.n_admitted += 1
            monitor.inc("decode/admitted_total", replica=self.replica)
            self._active.append((req, seq))
            self.max_concurrent = max(self.max_concurrent,
                                      len(self._active))
            self._emit_token(req, int(np.argmax(logits)))
            self._evict_finished()
        monitor.set_gauge("decode/cache_occupancy",
                          self.session.pool.used_fraction,
                          replica=self.replica)
        monitor.set_gauge("decode/active_seqs", len(self._active),
                          replica=self.replica)

    def _step(self) -> None:
        self._steps += 1
        t0 = time.monotonic()
        reqs = [r for r, _ in self._active]
        seqs = [s for _, s in self._active]
        tokens = np.asarray(
            [r.out[-1] if r.out else int(r.prompt[-1]) for r in reqs],
            np.int32)
        try:
            faults.fire("decode_step", replica=self.replica,
                        step=self._steps)
            logits = self.session.decode(seqs, tokens)
        except Exception as e:
            self._abort_inflight(e)
            return
        self.n_steps += 1
        monitor.inc("decode/steps_total", replica=self.replica)
        monitor.observe("decode/step_ms",
                        (time.monotonic() - t0) * 1e3,
                        replica=self.replica)
        monitor.set_gauge("serving/replica_heartbeat", time.time(),
                          replica=self.replica)
        if len(self._active) >= 2:
            self.shared_steps += 1
        for i, (req, _) in enumerate(self._active):
            self._emit_token(req, int(np.argmax(logits[i])))
        self._evict_finished()

    def _emit_token(self, req: _GenRequest, token: int) -> None:
        now = time.monotonic()
        first = not req.out
        req.out.append(token)
        self.n_tokens += 1
        monitor.inc("decode/tokens_total", replica=self.replica)
        if first:
            # the first token is prefill's output: its latency is
            # queue wait + prefill (decode/prefill_ms covers it), not
            # an inter-token gap — recording it would let admission
            # queueing contaminate the SLO histogram under overload
            req.t_last = now
            return
        dt_ms = (now - req.t_last) * 1e3
        req.t_last = now
        with self._lock:  # stats() iterates this deque concurrently
            self._intertoken_ms.append(dt_ms)
        monitor.observe("decode/intertoken_ms", dt_ms,
                        replica=self.replica)

    def _finished(self, req: _GenRequest) -> bool:
        if req.cancelled or len(req.out) >= req.max_new:
            return True
        eos = self.policy.eos_token
        return eos is not None and bool(req.out) and req.out[-1] == eos

    def _evict_finished(self) -> None:
        keep = []
        for req, seq in self._active:
            if self._finished(req):
                self.session.release(seq)
                self.n_evicted += 1
                monitor.inc("decode/evictions_total",
                            replica=self.replica)
                req.done.set()
            else:
                keep.append((req, seq))
        self._active = keep
        monitor.set_gauge("decode/active_seqs", len(self._active),
                          replica=self.replica)
        monitor.set_gauge("decode/cache_occupancy",
                          self.session.pool.used_fraction,
                          replica=self.replica)

    # -- failure plumbing ----------------------------------------------

    def _abort_inflight(self, err: BaseException,
                        extra: list | None = None) -> None:
        """A prefill/decode failure poisons the replica's device state
        (donated pool buffers may be consumed): fail EVERY in-flight
        stream and return its pages BEFORE the on_error hook runs —
        ``DecodeSession.reset_cache``'s precondition — then restart
        from the export or mark the replica dead.  ``extra`` carries a
        request that failed before it owned a sequence (the admit
        path)."""
        self.n_step_errors += 1
        monitor.inc("decode/step_errors_total", replica=self.replica)
        for _, seq in self._active:
            self.session.release(seq)
        failed, self._active = [r for r, _ in self._active], []
        self._fail_requests(list(extra or ()) + failed, err)
        monitor.set_gauge("decode/active_seqs", 0,
                          replica=self.replica)
        if self._on_error is None or not self._on_error(err):
            self._mark_dead()

    def _fail_requests(self, reqs, err: BaseException) -> None:
        for r in reqs:
            if not r.done.is_set():
                r.error = err
                r.done.set()

    def _mark_dead(self) -> None:
        with self._cond:
            self._dead = True
            self._cond.notify_all()
        self._fail_pending(Overloaded(
            f"decode replica {self.replica} died "
            "(restart budget exhausted)"))

    def _fail_pending(self, err: BaseException) -> None:
        with self._cond:
            pending, self._pending = list(self._pending), deque()
        self._fail_requests(pending, err)

    def _drain(self) -> None:
        """Stop path: evict everything, fail what was still running."""
        err = Overloaded(
            f"decode replica {self.replica} is shutting down")
        for req, seq in self._active:
            self.session.release(seq)
            self._fail_requests([req], err)
        self._active = []
        self._fail_pending(err)


class DecodeReplica:
    """One decode session + continuous batcher under the same
    restart-from-export supervision as ``serving/server.py Replica``:
    a step failure fails that step's sequences, then the replica
    reloads VERIFIED bytes from the export (budget ``max_restarts``)
    with a fresh page pool; budget exhausted = replica lost, the
    server routes around it."""

    def __init__(self, idx: int, export_dir: str, model, loaded,
                 policy: DecodePolicy | None = None,
                 max_restarts: int = 2, page_size: int = 16,
                 pages_per_seq: int = 8, max_seqs: int = 8,
                 prefill_buckets: tuple[int, ...] | None = None,
                 donate: bool = True):
        from theanompi_tpu.decode.session import DecodeSession

        self.idx = int(idx)
        self.export_dir = export_dir
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self.session = DecodeSession(
            model, params=loaded.params, version=loaded.version,
            page_size=page_size, pages_per_seq=pages_per_seq,
            max_seqs=max_seqs, prefill_buckets=prefill_buckets,
            donate=donate)
        self.batcher = ContinuousBatcher(
            self.session, policy, replica=self.idx,
            on_error=self._on_step_error)

    @property
    def alive(self) -> bool:
        return self.batcher.alive

    def generate(self, prompt, max_new: int | None = None) -> list[int]:
        return self.batcher.generate(prompt, max_new)

    def swap(self, version: int, params, model_state=None) -> None:
        self.session.swap(version, params, model_state)

    def _on_step_error(self, exc: BaseException) -> bool:
        from theanompi_tpu.serving.export import load_export

        self.restarts += 1
        monitor.inc("serving/replica_restarts_total", replica=self.idx)
        if self.restarts > self.max_restarts:
            print(f"[decode] replica {self.idx} exhausted "
                  f"{self.max_restarts} restarts "
                  f"({type(exc).__name__}: {exc}); marking it lost",
                  flush=True)
            return False
        try:
            # the version BEING SERVED, not the newest publish: a
            # restart must never become a side door past the reload
            # watcher's IncompatibleExport refusal (serving/server.py
            # Replica._on_batch_error has the same pin)
            loaded = load_export(self.export_dir,
                                 version=self.session.version)
        except Exception as e:
            print(f"[decode] replica {self.idx} restart-from-export "
                  f"failed ({type(e).__name__}: {e}); marking it lost",
                  flush=True)
            return False
        self.session.swap(loaded.version, loaded.params)
        # the failed step may have consumed the donated pool buffers —
        # restart on fresh pages (active sequences were already failed)
        self.session.reset_cache()
        print(f"[decode] replica {self.idx} restarted from export "
              f"v{loaded.version} after {type(exc).__name__} "
              f"(restart {self.restarts}/{self.max_restarts})",
              flush=True)
        return True
