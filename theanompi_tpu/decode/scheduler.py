"""ContinuousBatcher — iteration-level scheduling for token decode.

``serving/batcher.py`` coalesces REQUESTS: a batch forms, runs once,
and every member completes together.  Token generation breaks that
shape — sequences finish at different lengths, and a per-request batch
would hold 1-token stragglers hostage to 64-token neighbors.  This
scheduler batches ITERATIONS instead (the continuous-batching
discipline): between any two decode steps it may **admit** pending
prompts into free cache slots and **evict** finished sequences, so a
request admitted mid-stream shares its very first decode step with
whatever is already in flight (pinned by tests/test_decode.py and the
preflight decode smoke) and an evicted slot is refilled without
draining the batch.

What carries over from ``DynamicBatcher`` unchanged:

* **typed O(1) admission** — a full pending queue raises
  :class:`~theanompi_tpu.serving.batcher.Overloaded` immediately (the
  same class, so it rides the wire's ``err`` prefix identically);
* **deadline-from-oldest** — here the oldest pending prompt's wait is
  bounded by ONE decode step + its prefill, because admission runs
  every iteration rather than at batch boundaries;
* the **dead-replica contract** — a step failure hands the exception
  to ``on_error``; a falsy return marks the batcher dead, pending and
  future submits get ``Overloaded``, and the server routes around the
  corpse (``DecodeReplica`` owns restart-from-export, exactly like
  ``Replica``).

With a **draft session** (speculative decoding, docs/SERVING.md), the
per-iteration step becomes a ROUND: one draft ``propose`` call (k
greedy proposals), one bucketed target ``verify`` step (accept the
longest matching prefix, k+1 tokens on a full accept), one draft
``commit`` — still iteration-level, so admits/evicts interleave with
speculative rounds exactly as with plain steps, and a draft that
cannot be reloaded after a fault downgrades the replica to plain
decode instead of costing availability.

Telemetry: per-token inter-token latency (``decode/intertoken_ms`` —
the serving SLO, not request latency), tokens/steps counters, active/
pending gauges, cache occupancy and evictions, speculative accept
rate (``decode/accept_rate``, drafted/accepted counters) and
prefix-cache hit/miss/eviction + copy-on-write counters — all in the
monitor registry (docs/OBSERVABILITY.md) plus a host-side p50/p99
ring in ``stats()`` for the bench tools.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_condition, make_lock
from theanompi_tpu.decode.migrate import (
    IncompatiblePages,
    pages_incompatibility,
)
from theanompi_tpu.monitor import trace
from theanompi_tpu.resilience import faults
from theanompi_tpu.serving.batcher import Overloaded


@dataclasses.dataclass(frozen=True)
class DecodePolicy:
    """Admission/generation knobs for one decode replica."""

    #: admission bound: pending PROMPTS beyond this are rejected with
    #: Overloaded instead of queued (docs/SERVING.md overload
    #: semantics)
    max_pending: int = 32
    #: server-side cap on tokens generated per request
    max_new_cap: int = 256
    #: a blocked generate() gives up after this long
    submit_timeout_s: float = 120.0
    #: greedy decode stops early on this token (None = length-only)
    eos_token: int | None = None
    #: draft tokens per speculative round (used only when the replica
    #: has a draft session; k drafts verify in ONE target step and the
    #: verify's own argmax rides along, so a full accept advances a
    #: stream k+1 tokens per step — docs/SERVING.md "Speculative
    #: decode")
    speculate_k: int = 4
    #: max prompts coalesced into ONE batched prefill per admission
    #: round (``DecodeSession.admit_batch``); 1 = the pre-batching
    #: serial path, one prefill program call per prompt
    prefill_batch: int = 8
    #: how long the OLDEST pending prompt may wait for company before
    #: its batch launches regardless of occupancy — DynamicBatcher's
    #: deadline-from-oldest, applied to admission (docs/SERVING.md
    #: "Batched prefill")
    prefill_delay_ms: float = 2.0


class MigratedStream:
    """Returned (never raised) by generate/generate_adopted when the
    replica DRAINED mid-stream (scale-down page re-migration,
    docs/SERVING.md): ``tokens`` are the already-emitted tokens MINUS
    the pending one, which travels as the manifest's ``first_token`` —
    the router stitches ``tokens + survivor_output`` for a result
    byte-identical to an undrained run."""

    __slots__ = ("tokens", "manifest", "k", "v")

    def __init__(self, tokens: list[int], manifest: dict, k, v):
        self.tokens = tokens
        self.manifest = manifest
        self.k = k
        self.v = v


class _GenRequest:
    __slots__ = ("prompt", "max_new", "out", "done", "error", "t0",
                 "t_last", "cancelled", "adopted", "migrated")

    def __init__(self, prompt: np.ndarray, max_new: int,
                 adopted: tuple | None = None):
        self.prompt = prompt
        self.max_new = int(max_new)
        #: page migration (decode/migrate.py): ``(manifest, k, v)``
        #: when this stream was prefilled elsewhere — admission adopts
        #: the pages instead of running a local prefill
        self.adopted = adopted
        #: set by the drain path: this stream left as pages, the
        #: parked caller returns the payload instead of tokens
        self.migrated: MigratedStream | None = None
        self.out: list[int] = []
        self.done = threading.Event()
        self.error: BaseException | None = None
        self.t0 = time.monotonic()
        self.t_last = self.t0
        #: set by an abandoning client thread, read by the scheduler at
        #: the next step boundary — a benign boolean race (either the
        #: scheduler sees it this step or the next)
        self.cancelled = False


class ContinuousBatcher:
    """One decode replica's scheduler thread + admission queue.

    ``session`` is a :class:`~theanompi_tpu.decode.session.DecodeSession`;
    its cache state is owned by THIS object's single scheduler thread.
    ``generate`` is the client-side entry (any thread)."""

    def __init__(self, session, policy: DecodePolicy | None = None,
                 replica: int = 0, on_error=None, draft_session=None):
        self.session = session
        self.policy = policy or DecodePolicy()
        self.replica = int(replica)
        self._on_error = on_error
        #: draft DecodeSession (speculative decoding) or None; owned
        #: by the scheduler thread like the target session — a restart
        #: that cannot reload the draft clears it (speculation off,
        #: replica keeps serving)
        self._draft = draft_session
        if draft_session is not None:
            k = int(self.policy.speculate_k)
            for s, who in ((session, "target"), (draft_session, "draft")):
                if not 1 <= k <= s.window - 1:
                    raise ValueError(
                        f"speculate_k {k} outside [1, window-1="
                        f"{s.window - 1}] for the {who} session")
        self._pending: deque[_GenRequest] = deque()  # guarded_by: self._lock
        self._lock = make_lock("ContinuousBatcher._lock")
        self._cond = make_condition(self._lock)
        self._dead = False                           # guarded_by: self._lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # scheduler-thread-owned live set:
        # (request, target _Seq, draft _Seq | None)
        self._active: list[tuple[_GenRequest, object, object]] = []
        self._steps = 0
        # plain-int stats (torn reads of monotonic ints are harmless
        # for stats(), the DynamicBatcher convention)
        self.n_tokens = 0
        self.n_steps = 0
        self.n_admitted = 0
        self.n_evicted = 0
        self.n_overloaded = 0
        self.n_step_errors = 0
        #: steps whose decode batch held >= 2 sequences — the
        #: iteration-level-sharing proof the preflight smoke asserts
        self.shared_steps = 0
        self.max_concurrent = 0
        #: speculative accounting (utils/token_accounting.py): drafted
        #: = k per sequence per round, accepted = those the verify
        #: step kept; emitted tokens ride the ordinary token counters
        self.n_drafted = 0
        self.n_draft_accepted = 0
        #: page migration (disaggregated serving): streams whose
        #: prefill arrived as wire frames / typed-refused manifests
        self.n_adopted = 0
        self.n_adopt_refused = 0
        #: batched prefill accounting: admission rounds that ran ONE
        #: program call over >= 1 prompts, the largest such batch, and
        #: prompt-token/wall-second totals (the bench's aggregate
        #: prefill-throughput axis)
        self.n_prefill_batches = 0
        self.max_prefill_batch = 0
        self.prefill_tokens = 0
        self.prefill_s = 0.0
        #: scale-down page re-migration: live streams exported as
        #: MigratedStream payloads by drain_migrate()
        self.n_migrated_out = 0
        #: set by drain_migrate(); terminal — admission refuses, the
        #: scheduler exports live streams at the next step boundary
        self._draining = False
        #: next coalescing deadline while admission holds a partial
        #: batch for company (read by _loop for its wait bound)
        self._admit_deadline = 0.0
        #: last-seen cow_copies across both sessions (delta -> monitor)
        self._cow_seen = 0
        self._intertoken_ms: deque[float] = deque(maxlen=4096)  # guarded_by: self._lock
        self._ttft_ms: deque[float] = deque(maxlen=4096)  # guarded_by: self._lock

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "ContinuousBatcher":
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"decode-scheduler-{self.replica}")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        self._fail_pending(Overloaded(
            f"decode replica {self.replica} is shutting down"))

    @property
    def alive(self) -> bool:
        with self._lock:
            return not self._dead and not self._stop.is_set()

    def reset_intertoken(self) -> None:
        """Drop the inter-token AND time-to-first-token latency rings
        (bench seam: a warm pass compiles programs, and those
        multi-second gaps would otherwise sit in the measured pass's
        p99)."""
        with self._lock:
            self._intertoken_ms.clear()
            self._ttft_ms.clear()

    def stats(self) -> dict:
        from theanompi_tpu.utils.token_accounting import (
            speculative_accounting,
        )

        with self._lock:
            pending = len(self._pending)
            lat = (np.sort(np.asarray(self._intertoken_ms, np.float64))
                   if self._intertoken_ms else np.zeros((0,)))
            ttft = (np.sort(np.asarray(self._ttft_ms, np.float64))
                    if self._ttft_ms else np.zeros((0,)))

        def _pcts(a):
            def pk(q):
                return (float(a[min(len(a) - 1, int(q * len(a)))])
                        if len(a) else None)
            return {"p50": pk(0.50), "p99": pk(0.99), "count": len(a)}
        pc = self.session.prefix_cache
        # one-read snapshot: disable_speculation() nulls _draft on the
        # scheduler thread while stats() runs on an RPC handler thread
        draft = self._draft
        return {
            "replica": self.replica,
            "alive": self.alive,
            "tokens": self.n_tokens,
            "steps": self.n_steps,
            "admitted": self.n_admitted,
            "evicted": self.n_evicted,
            "overloaded": self.n_overloaded,
            "step_errors": self.n_step_errors,
            "shared_steps": self.shared_steps,
            "max_concurrent": self.max_concurrent,
            "adopted": self.n_adopted,
            "adopt_refused": self.n_adopt_refused,
            "active": len(self._active),
            "pending": pending,
            "free_pages": self.session.pool.free_pages,
            "intertoken_ms": _pcts(lat),
            "ttft_ms": _pcts(ttft),
            "prefill_batches": self.n_prefill_batches,
            "max_prefill_batch": self.max_prefill_batch,
            "prefill_tokens": self.prefill_tokens,
            "prefill_s": self.prefill_s,
            "drain_migrated": self.n_migrated_out,
            "draining": self._draining,
            "compiles": dict(self.session.compiles),
            "draft_compiles": (dict(draft.compiles)
                               if draft is not None else None),
            "speculative": draft is not None,
            # one arithmetic with bench_lm/bench_serving: emitted
            # tokens are the throughput axis; rejected drafts are
            # compute, not output
            "speculation": speculative_accounting(
                self.n_tokens, self.n_drafted, self.n_draft_accepted),
            "prefix_cache": (None if pc is None else {
                "hits": pc.hits, "misses": pc.misses,
                "evictions": pc.evictions, "entries": len(pc),
                "cached_pages": pc.cached_pages,
            }),
            "cow_copies": (self.session.cow_copies
                           + (draft.cow_copies
                              if draft is not None else 0)),
        }

    # -- client side ----------------------------------------------------

    def generate(self, prompt, max_new: int | None = None):
        """Greedy-decode up to ``max_new`` tokens after ``prompt``;
        blocks until the sequence finishes and returns the token list.
        Raises :class:`Overloaded` on admission rejection or re-raises
        the step error that consumed this request.  If the replica
        drained mid-stream (scale-down), returns a
        :class:`MigratedStream` instead of tokens."""
        if trace.enabled():
            # under tracing, a GENERATE handled via rpc_handle (the
            # serving plane) gets a decode-side child span here — the
            # client -> server -> replica -> batcher chain closes at
            # the batcher.  Gated so the untraced hot path (and its
            # metric stream) is unchanged.
            with monitor.span("decode_generate", replica=self.replica):
                return self._generate(prompt, max_new)
        return self._generate(prompt, max_new)

    def _generate(self, prompt, max_new: int | None = None):
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        max_new = int(max_new if max_new is not None
                      else self.policy.max_new_cap)
        max_new = min(max_new, self.policy.max_new_cap)
        if prompt.shape[0] < 1 or max_new < 1:
            raise ValueError("need a non-empty prompt and max_new >= 1")
        if prompt.shape[0] > self.session.max_prompt:
            raise ValueError(
                f"prompt length {prompt.shape[0]} exceeds the largest "
                f"prefill bucket {self.session.max_prompt}")
        if prompt.shape[0] + max_new > self.session.max_len:
            raise ValueError(
                f"prompt+max_new {prompt.shape[0] + max_new} exceeds "
                f"the model's max_len {self.session.max_len} "
                "(positional table)")
        req = _GenRequest(prompt, max_new)
        with self._cond:
            if self._dead or self._draining or self._stop.is_set():
                self.n_overloaded += 1
                monitor.inc("decode/overloaded_total",
                            replica=self.replica)
                raise Overloaded(
                    f"decode replica {self.replica} is not serving"
                    + (" (draining)" if self._draining else ""))
            if len(self._pending) >= self.policy.max_pending:
                self.n_overloaded += 1
                monitor.inc("decode/overloaded_total",
                            replica=self.replica)
                raise Overloaded(
                    f"decode replica {self.replica} admission queue is "
                    f"full ({self.policy.max_pending} pending); "
                    "rejecting instead of queueing unboundedly")
            self._pending.append(req)
            monitor.set_gauge("decode/pending", len(self._pending),
                              replica=self.replica)
            self._cond.notify_all()
        if not req.done.wait(self.policy.submit_timeout_s):
            with self._cond:
                try:
                    self._pending.remove(req)
                except ValueError:
                    # already admitted: the scheduler evicts it at the
                    # next step boundary via the cancelled flag
                    req.cancelled = True
            raise TimeoutError(
                f"generate timed out after "
                f"{self.policy.submit_timeout_s}s on decode replica "
                f"{self.replica}")
        if req.error is not None:
            raise req.error
        if req.migrated is not None:
            # the replica drained mid-stream: hand the partial output
            # + exported pages up for the router to re-dispatch
            return req.migrated
        return req.out

    def generate_adopted(self, manifest: dict, k, v,
                         max_new: int | None = None):
        """Adopt a migrated prefill (decode/migrate.py) and greedy-
        decode up to ``max_new`` further tokens.  The manifest's
        ``first_token`` (the sender's prefill argmax) is emitted as
        token 0, so the stream's output is byte-identical to
        :meth:`generate` over the same prompt on one replica.  Raises
        the typed :class:`IncompatiblePages` when the pages don't fit
        this replica's pool — a per-stream refusal, the replica and
        the connection keep serving — and :class:`Overloaded` on
        admission rejection, exactly like :meth:`generate`."""
        if trace.enabled():
            with monitor.span("decode_generate", replica=self.replica):
                return self._generate_adopted(manifest, k, v, max_new)
        return self._generate_adopted(manifest, k, v, max_new)

    def _generate_adopted(self, manifest, k, v,
                          max_new: int | None = None):
        faults.fire("page_migrate", side="adopt", replica=self.replica)
        # geometry refusal BEFORE enqueue: a stream that can never be
        # adopted must not occupy a pending slot (O(1), no data copy)
        reason = pages_incompatibility(manifest, k, v,
                                       self.session.cfg)
        if reason is not None:
            self.n_adopt_refused += 1
            monitor.inc("decode/adopt_refused_total",
                        replica=self.replica)
            raise IncompatiblePages(reason)
        max_new = int(max_new if max_new is not None
                      else self.policy.max_new_cap)
        max_new = min(max_new, self.policy.max_new_cap)
        if max_new < 1:
            raise ValueError("need max_new >= 1")
        length = int(manifest["length"])
        if length + max_new > self.session.max_len:
            raise ValueError(
                f"adopted length+max_new {length + max_new} exceeds "
                f"the model's max_len {self.session.max_len} "
                "(positional table)")
        prompt = np.asarray(manifest["prompt"], np.int32).reshape(-1)
        req = _GenRequest(prompt, max_new, adopted=(manifest, k, v))
        with self._cond:
            if self._dead or self._draining or self._stop.is_set():
                self.n_overloaded += 1
                monitor.inc("decode/overloaded_total",
                            replica=self.replica)
                raise Overloaded(
                    f"decode replica {self.replica} is not serving"
                    + (" (draining)" if self._draining else ""))
            if len(self._pending) >= self.policy.max_pending:
                self.n_overloaded += 1
                monitor.inc("decode/overloaded_total",
                            replica=self.replica)
                raise Overloaded(
                    f"decode replica {self.replica} admission queue is "
                    f"full ({self.policy.max_pending} pending); "
                    "rejecting instead of queueing unboundedly")
            self._pending.append(req)
            monitor.set_gauge("decode/pending", len(self._pending),
                              replica=self.replica)
            self._cond.notify_all()
        if not req.done.wait(self.policy.submit_timeout_s):
            with self._cond:
                try:
                    self._pending.remove(req)
                except ValueError:
                    req.cancelled = True
            raise TimeoutError(
                f"generate_adopted timed out after "
                f"{self.policy.submit_timeout_s}s on decode replica "
                f"{self.replica}")
        if req.error is not None:
            raise req.error
        if req.migrated is not None:
            # the replica drained mid-stream: hand the partial output
            # + exported pages up for the router to re-dispatch
            return req.migrated
        return req.out

    # -- scheduler thread ----------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self._draining:
                self._migrate_out()
            else:
                self._admit()
            if not self._active:
                with self._cond:
                    if self._stop.is_set():
                        continue
                    if not self._pending:
                        self._cond.wait(0.25)
                        monitor.set_gauge("serving/replica_heartbeat",
                                          time.time(),
                                          replica=self.replica)
                    else:
                        # pending held back by the coalescing deadline
                        # — sleep only until it expires (an arrival
                        # notifies and may fill the batch early); the
                        # floor guards the can't-admit-yet edge
                        remaining = (self._admit_deadline
                                     - time.monotonic())
                        self._cond.wait(min(0.25, max(remaining,
                                                      0.002)))
                continue
            self._step()
        self._drain()

    def _take_pending(self) -> _GenRequest | None:
        with self._cond:
            req = self._pending.popleft() if self._pending else None
            monitor.set_gauge("decode/pending", len(self._pending),
                              replica=self.replica)
            return req

    def _prefix_metrics(self) -> tuple[int, int, int]:
        pc = self.session.prefix_cache
        return (0, 0, 0) if pc is None else (pc.hits, pc.misses,
                                             pc.evictions)

    def _admit(self) -> None:
        """Admit pending prompts into free slots — every iteration, so
        the oldest waiter's deadline is one decode step away.  With
        ``prefill_batch > 1`` an admission round GATHERS up to that
        many plain prompts and runs them as ONE
        :meth:`~theanompi_tpu.decode.session.DecodeSession.admit_batch`
        program call (adopted streams still admit singly — their pages
        scatter, there is no prefill to batch).  With a draft session
        the prompts are admitted into BOTH caches (same geometry, so a
        target admit implies draft capacity)."""
        pb = max(1, int(self.policy.prefill_batch))
        while not self._stop.is_set():
            if pb > 1 and self._hold_for_coalescing(pb):
                break
            batch: list[_GenRequest] = []
            adopted_req: _GenRequest | None = None
            while (len(self._active) + len(batch)
                       < self.session.cfg.max_seqs
                   and len(batch) < pb
                   and self.session.can_admit(len(batch) + 1)
                   and (self._draft is None
                        or self._draft.can_admit(len(batch) + 1))
                   and not self._stop.is_set()):
                req = self._take_pending()
                if req is None:
                    break
                if req.cancelled:
                    continue
                if req.adopted is not None:
                    # adopted streams admit singly: flush the gathered
                    # batch first so arrival order is preserved
                    adopted_req = req
                    break
                if self._shares_page_prefix(req, batch):
                    # a same-round row cannot hit a prefix an earlier
                    # row is about to register (inserts land after the
                    # program runs): defer ONE round so it admits as a
                    # cache hit sharing pages instead of refilling them
                    with self._cond:
                        self._pending.appendleft(req)
                    break
                batch.append(req)
            if batch and not self._admit_plain(batch):
                return
            if adopted_req is not None:
                if not self._admit_adopted(adopted_req):
                    return
                continue
            if not batch:
                break
        monitor.set_gauge("decode/cache_occupancy",
                          self.session.pool.used_fraction,
                          replica=self.replica)
        monitor.set_gauge("decode/active_seqs", len(self._active),
                          replica=self.replica)

    def _shares_page_prefix(self, req: _GenRequest, batch) -> bool:
        """True when ``req`` shares a >= 1-page aligned prompt prefix
        with a row already gathered this round — the page-sharing
        deferral above (no effect with the prefix cache off)."""
        if self.session.prefix_cache is None or not batch:
            return False
        ps = int(self.session.cfg.page_size)
        for r in batch:
            a, b = r.prompt, req.prompt
            n = min(int(a.shape[0]), int(b.shape[0]))
            if n < ps:
                continue
            eq = a[:n] == b[:n]
            m = n if eq.all() else int(np.argmin(eq))
            if m >= ps:
                return True
        return False

    def _hold_for_coalescing(self, pb: int) -> bool:
        """DynamicBatcher's deadline-from-oldest applied to admission:
        while the OLDEST pending prompt is younger than
        ``prefill_delay_ms`` and more batchable room remains, hold off
        so a burst coalesces into one prefill program call instead of
        several small ones.  Never holds an adopted stream (no prefill
        to batch), a full batch, or past the deadline — the delay
        bounds added time-to-first-token exactly."""
        delay_s = float(self.policy.prefill_delay_ms) / 1e3
        if delay_s <= 0:
            return False
        with self._lock:
            n = len(self._pending)
            if n == 0 or self._pending[0].adopted is not None:
                return False
            oldest_t0 = self._pending[0].t0
        room = min(pb,
                   self.session.cfg.max_seqs - len(self._active))
        if n >= room:
            return False
        deadline = oldest_t0 + delay_s
        if time.monotonic() >= deadline:
            return False
        self._admit_deadline = deadline
        return True

    def _admit_plain(self, batch: list[_GenRequest]) -> bool:
        """One admission round: N prompts -> ONE batched prefill
        program call (``prefill_batch == 1`` keeps the pre-batching
        serial ``admit`` path, byte-for-byte — the bench's comparison
        leg).  Returns False only when the poisoned-device path ran
        (``_abort_inflight``), mirroring ``_admit_adopted``."""
        serial = max(1, int(self.policy.prefill_batch)) == 1
        t0 = time.monotonic()
        h0, m0, e0 = self._prefix_metrics()
        try:
            if serial:
                admitted = [self.session.admit(batch[0].prompt)]
            else:
                admitted = self.session.admit_batch(
                    [r.prompt for r in batch])
        except Exception as e:
            if isinstance(e, ValueError):
                # a bad request must not kill the replica (lengths
                # were validated at submit, so this is defensive)
                self._fail_requests(batch, e)
                return True
            self._abort_inflight(e, extra=batch)
            return False
        dseqs: list = [None] * len(batch)
        if self._draft is not None:
            try:
                if serial:
                    dseq, _ = self._draft.admit(batch[0].prompt)
                    dseqs = [dseq]
                else:
                    dseqs = [s for s, _ in self._draft.admit_batch(
                        [r.prompt for r in batch])]
            except Exception as e:
                for seq, _ in admitted:
                    self.session.release(seq)
                if isinstance(e, ValueError):
                    self._fail_requests(batch, e)
                    return True
                self._abort_inflight(e, extra=batch)
                return False
        h1, m1, e1 = self._prefix_metrics()
        if h1 > h0:
            monitor.inc("decode/prefix_cache_hits_total",
                        h1 - h0, replica=self.replica)
        if m1 > m0:
            monitor.inc("decode/prefix_cache_misses_total",
                        m1 - m0, replica=self.replica)
        if e1 > e0:
            monitor.inc("decode/prefix_cache_evictions_total",
                        e1 - e0, replica=self.replica)
        dt = time.monotonic() - t0
        monitor.observe("decode/prefill_ms", dt * 1e3,
                        replica=self.replica)
        monitor.observe("decode/prefill_batch_occupancy",
                        float(len(batch)), replica=self.replica)
        self.n_prefill_batches += 1
        self.max_prefill_batch = max(self.max_prefill_batch,
                                     len(batch))
        self.prefill_tokens += sum(int(r.prompt.shape[0])
                                   for r in batch)
        self.prefill_s += dt
        self.n_admitted += len(batch)
        monitor.inc("decode/admitted_total", float(len(batch)),
                    replica=self.replica)
        for req, (seq, logits), dseq in zip(batch, admitted, dseqs):
            self._active.append((req, seq, dseq))
            self._emit_token(req, int(np.argmax(logits)))
        self.max_concurrent = max(self.max_concurrent,
                                  len(self._active))
        self._evict_finished()
        return True

    # -- scale-down page re-migration ----------------------------------

    def drain_migrate(self) -> None:
        """Scale-down hand-off (any thread): admission starts refusing
        with Overloaded, pending requests fail with it (the router's
        existing failover re-dispatches them), and at the next step
        boundary the scheduler exports every LIVE stream's pages + a
        resume manifest — the parked ``generate`` calls return
        :class:`MigratedStream` payloads for the router to re-dispatch
        onto a survivor, byte-identical.  Terminal: a draining replica
        never resumes admission."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def _migrate_out(self) -> None:
        """Drain leg (scheduler thread, step boundary): every live
        stream leaves as pages + a resume manifest — prompt plus the
        tokens emitted so far, with the PENDING token (emitted to the
        caller but not yet decoded) travelling as the manifest's
        ``first_token``.  The survivor re-emits exactly that token
        first, so the stitched ``tokens + survivor_output`` is
        byte-identical to finishing here."""
        from theanompi_tpu.decode.migrate import page_manifest

        active, self._active = self._active, []
        for req, seq, dseq in active:
            if self._finished(req):
                self.session.release(seq)
                if dseq is not None and self._draft is not None:
                    self._draft.release(dseq)
                self.n_evicted += 1
                monitor.inc("decode/evictions_total",
                            replica=self.replica)
                req.done.set()
                continue
            try:
                k, v = self.session.export_pages(seq)
                # invariant: seq.length == len(prompt) + len(out) - 1
                # for a live stream, so the resume prompt is exactly
                # the attended positions and out[-1] is the pending
                # token the survivor will decode first
                resume = np.concatenate(
                    [req.prompt,
                     np.asarray(req.out[:-1], np.int32)])
                manifest = page_manifest(
                    self.session.cfg, resume, seq.length,
                    int(req.out[-1]), version=self.session.version)
                req.migrated = MigratedStream(
                    [int(t) for t in req.out[:-1]], manifest, k, v)
                self.n_migrated_out += 1
                monitor.inc("decode/drain_migrated_total",
                            replica=self.replica)
            except Exception as e:
                req.error = e
            self.session.release(seq)
            if dseq is not None and self._draft is not None:
                self._draft.release(dseq)
            req.done.set()
        monitor.set_gauge("decode/active_seqs", 0,
                          replica=self.replica)
        self._fail_pending(Overloaded(
            f"decode replica {self.replica} is draining "
            "(scale-down)"))

    def _admit_adopted(self, req: _GenRequest) -> bool:
        """Admission for a migrated stream (decode/migrate.py): the
        shipped pages scatter into the pool instead of running a local
        prefill, and the sender's first token is emitted verbatim.
        Returns False only when the poisoned-device path ran
        (``_abort_inflight``) and the admit loop must stop."""
        manifest, kp, vp = req.adopted
        t0 = time.monotonic()
        try:
            seq = self.session.adopt_pages(manifest, kp, vp)
        except (IncompatiblePages, ValueError) as e:
            # per-stream refusal — the replica (and its connection)
            # keeps serving; geometry was pre-checked at submit, so
            # this only fires on races like a mid-flight hot reload
            self.n_adopt_refused += 1
            monitor.inc("decode/adopt_refused_total",
                        replica=self.replica)
            self._fail_requests([req], e)
            return True
        except Exception as e:
            self._abort_inflight(e, extra=[req])
            return False
        dseq = None
        if self._draft is not None:
            # the draft is small and prefills the prompt locally — the
            # TARGET's prefill is what migration offloads
            try:
                dseq, _ = self._draft.admit(req.prompt)
            except Exception as e:
                self.session.release(seq)
                if isinstance(e, ValueError):
                    self._fail_requests([req], e)
                    return True
                self._abort_inflight(e, extra=[req])
                return False
        monitor.observe("decode/adopt_ms",
                        (time.monotonic() - t0) * 1e3,
                        replica=self.replica)
        self.n_adopted += 1
        monitor.inc("decode/pages_adopted_total",
                    self.session.cfg.pages_per_seq,
                    replica=self.replica)
        self.n_admitted += 1
        monitor.inc("decode/admitted_total", replica=self.replica)
        self._active.append((req, seq, dseq))
        self.max_concurrent = max(self.max_concurrent,
                                  len(self._active))
        self._emit_token(req, int(manifest["first_token"]))
        self._evict_finished()
        return True

    def _step(self) -> None:
        if self._draft is not None:
            self._spec_step()
            return
        self._steps += 1
        t0 = time.monotonic()
        reqs = [r for r, _, _ in self._active]
        seqs = [s for _, s, _ in self._active]
        tokens = np.asarray(
            [r.out[-1] if r.out else int(r.prompt[-1]) for r in reqs],
            np.int32)
        try:
            faults.fire("decode_step", replica=self.replica,
                        step=self._steps)
            logits = self.session.decode(seqs, tokens)
        except Exception as e:
            self._abort_inflight(e)
            return
        self.n_steps += 1
        monitor.inc("decode/steps_total", replica=self.replica)
        monitor.observe("decode/step_ms",
                        (time.monotonic() - t0) * 1e3,
                        replica=self.replica)
        monitor.set_gauge("serving/replica_heartbeat", time.time(),
                          replica=self.replica)
        if len(self._active) >= 2:
            self.shared_steps += 1
        for i, (req, _, _) in enumerate(self._active):
            self._emit_token(req, int(np.argmax(logits[i])))
        self._emit_cow_delta()
        self._evict_finished()

    def _spec_step(self) -> None:
        """One speculative round for every active sequence: k draft
        proposals (one draft program call), ONE bucketed target verify
        step, then the draft cache commits the accepted prefix.  Every
        sequence advances by its accept count + 1 (the verify step's
        own argmax token rides along), so a full accept yields k+1
        tokens for one target step."""
        self._steps += 1
        k = int(self.policy.speculate_k)
        t0 = time.monotonic()
        reqs = [r for r, _, _ in self._active]
        seqs = [s for _, s, _ in self._active]
        dseqs = [d for _, _, d in self._active]
        pending = np.asarray(
            [r.out[-1] if r.out else int(r.prompt[-1]) for r in reqs],
            np.int32)
        try:
            faults.fire("decode_step", replica=self.replica,
                        step=self._steps)
            drafts = self._draft.propose(dseqs, pending, k)
            y, counts = self.session.verify(seqs, pending, drafts)
            self._draft.commit(dseqs, counts)
        except Exception as e:
            self._abort_inflight(e)
            return
        self.n_steps += 1
        monitor.inc("decode/steps_total", replica=self.replica)
        monitor.observe("decode/step_ms",
                        (time.monotonic() - t0) * 1e3,
                        replica=self.replica)
        monitor.set_gauge("serving/replica_heartbeat", time.time(),
                          replica=self.replica)
        if len(self._active) >= 2:
            self.shared_steps += 1
        for i, (req, _, _) in enumerate(self._active):
            accepted = int(counts[i]) - 1
            self.n_drafted += k
            self.n_draft_accepted += accepted
            monitor.inc("decode/draft_tokens_total", k,
                        replica=self.replica)
            if accepted:
                monitor.inc("decode/draft_accepted_total", accepted,
                            replica=self.replica)
            monitor.observe("decode/accept_rate", accepted / k,
                            replica=self.replica)
            for j in range(int(counts[i])):
                if self._finished(req):
                    # max_new / eos reached mid-run: the device wrote
                    # the extra positions' K/V, but the sequence is
                    # evicted below, so the surplus is unobservable —
                    # emitted output stays byte-identical to the
                    # non-speculative oracle
                    break
                self._emit_token(req, int(y[i, j]))
        self._emit_cow_delta()
        self._evict_finished()

    def _emit_cow_delta(self) -> None:
        cow = self.session.cow_copies + (self._draft.cow_copies
                                         if self._draft is not None
                                         else 0)
        if cow > self._cow_seen:
            monitor.inc("decode/cow_copies_total",
                        cow - self._cow_seen, replica=self.replica)
            self._cow_seen = cow

    def disable_speculation(self) -> None:
        """Drop the draft session (restart path when the draft export
        cannot be reloaded): the replica keeps serving, plain decode —
        an accelerator must never cost availability.  Scheduler-thread
        only (like every cache mutation); active draft sequences are
        released."""
        if self._draft is None:
            return
        for _, _, dseq in self._active:
            if dseq is not None:
                self._draft.release(dseq)
        self._active = [(r, s, None) for r, s, _ in self._active]
        self._draft = None
        # the monitor delta tracked target+draft COW as one sum;
        # re-anchor on the target alone or the next (sum < seen)
        # comparisons silently drop real target copies
        self._cow_seen = self.session.cow_copies

    def _emit_token(self, req: _GenRequest, token: int) -> None:
        now = time.monotonic()
        first = not req.out
        req.out.append(token)
        self.n_tokens += 1
        monitor.inc("decode/tokens_total", replica=self.replica)
        if first:
            # the first token is prefill's output: its latency is
            # queue wait + prefill (decode/prefill_ms covers it), not
            # an inter-token gap — recording it would let admission
            # queueing contaminate the SLO histogram under overload.
            # It IS time-to-first-token, the axis batched prefill
            # trades coalescing delay against — tracked separately.
            ttft_ms = (now - req.t0) * 1e3
            with self._lock:
                self._ttft_ms.append(ttft_ms)
            monitor.observe("decode/ttft_ms", ttft_ms,
                            replica=self.replica)
            req.t_last = now
            return
        dt_ms = (now - req.t_last) * 1e3
        req.t_last = now
        with self._lock:  # stats() iterates this deque concurrently
            self._intertoken_ms.append(dt_ms)
        monitor.observe("decode/intertoken_ms", dt_ms,
                        replica=self.replica)

    def _finished(self, req: _GenRequest) -> bool:
        if req.cancelled or len(req.out) >= req.max_new:
            return True
        eos = self.policy.eos_token
        return eos is not None and bool(req.out) and req.out[-1] == eos

    def _evict_finished(self) -> None:
        keep = []
        for req, seq, dseq in self._active:
            if self._finished(req):
                self.session.release(seq)
                if dseq is not None and self._draft is not None:
                    self._draft.release(dseq)
                self.n_evicted += 1
                monitor.inc("decode/evictions_total",
                            replica=self.replica)
                req.done.set()
            else:
                keep.append((req, seq, dseq))
        self._active = keep
        monitor.set_gauge("decode/active_seqs", len(self._active),
                          replica=self.replica)
        monitor.set_gauge("decode/cache_occupancy",
                          self.session.pool.used_fraction,
                          replica=self.replica)

    # -- failure plumbing ----------------------------------------------

    def _abort_inflight(self, err: BaseException,
                        extra: list | None = None) -> None:
        """A prefill/decode failure poisons the replica's device state
        (donated pool buffers may be consumed): fail EVERY in-flight
        stream and return its pages BEFORE the on_error hook runs —
        ``DecodeSession.reset_cache``'s precondition — then restart
        from the export or mark the replica dead.  ``extra`` carries a
        request that failed before it owned a sequence (the admit
        path)."""
        self.n_step_errors += 1
        monitor.inc("decode/step_errors_total", replica=self.replica)
        for _, seq, dseq in self._active:
            self.session.release(seq)
            if dseq is not None and self._draft is not None:
                self._draft.release(dseq)
        failed, self._active = [r for r, _, _ in self._active], []
        self._fail_requests(list(extra or ()) + failed, err)
        monitor.set_gauge("decode/active_seqs", 0,
                          replica=self.replica)
        if self._on_error is None or not self._on_error(err):
            self._mark_dead()

    def _fail_requests(self, reqs, err: BaseException) -> None:
        for r in reqs:
            if not r.done.is_set():
                r.error = err
                r.done.set()

    def _mark_dead(self) -> None:
        with self._cond:
            self._dead = True
            self._cond.notify_all()
        self._fail_pending(Overloaded(
            f"decode replica {self.replica} died "
            "(restart budget exhausted)"))

    def _fail_pending(self, err: BaseException) -> None:
        with self._cond:
            pending, self._pending = list(self._pending), deque()
        self._fail_requests(pending, err)

    def _drain(self) -> None:
        """Stop path: evict everything, fail what was still running."""
        err = Overloaded(
            f"decode replica {self.replica} is shutting down")
        for req, seq, dseq in self._active:
            self.session.release(seq)
            if dseq is not None and self._draft is not None:
                self._draft.release(dseq)
            self._fail_requests([req], err)
        self._active = []
        self._fail_pending(err)


class DecodeReplica:
    """One decode session + continuous batcher under the same
    restart-from-export supervision as ``serving/server.py Replica``:
    a step failure fails that step's sequences, then the replica
    reloads VERIFIED bytes from the export (budget ``max_restarts``)
    with a fresh page pool; budget exhausted = replica lost, the
    server routes around it."""

    def __init__(self, idx: int, export_dir: str, model, loaded,
                 policy: DecodePolicy | None = None,
                 max_restarts: int = 2, page_size: int = 16,
                 pages_per_seq: int = 8, max_seqs: int = 8,
                 prefill_buckets: tuple[int, ...] | None = None,
                 donate: bool = True, draft_export_dir: str | None = None,
                 prefix_cache: bool = True,
                 fleet_cache: str | None = None):
        from theanompi_tpu.decode.session import DecodeSession
        from theanompi_tpu.serving.export import (
            IncompatibleExport,
            build_model_from_meta,
            draft_incompatibility,
            load_export,
        )

        self.idx = int(idx)
        self.export_dir = export_dir
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self.session = DecodeSession(
            model, params=loaded.params, version=loaded.version,
            page_size=page_size, pages_per_seq=pages_per_seq,
            max_seqs=max_seqs, prefill_buckets=prefill_buckets,
            donate=donate, prefix_cache=prefix_cache)
        if fleet_cache:
            # fleet-wide prefix cache (decode/fleetcache.py): local
            # misses consult the prefill-fleet authority, cold
            # prefills register their page-aligned prefixes
            from theanompi_tpu.decode.fleetcache import FleetCacheClient
            self.session.fleet = FleetCacheClient(fleet_cache)
        #: speculative decoding: a second (small) decode-capable
        #: export proposes k tokens per round; same cache geometry so
        #: a target admit implies draft capacity
        self.draft_export_dir = draft_export_dir
        self.draft_session = None
        self.draft_meta = None
        if draft_export_dir:
            dloaded = load_export(draft_export_dir)
            reason = draft_incompatibility(loaded.meta, dloaded.meta)
            if reason is not None:
                raise IncompatibleExport(
                    f"draft export {draft_export_dir} "
                    f"v{dloaded.version}: {reason}")
            dmodel = build_model_from_meta(dloaded.meta)
            self.draft_session = DecodeSession(
                dmodel, params=dloaded.params, version=dloaded.version,
                page_size=page_size, pages_per_seq=pages_per_seq,
                max_seqs=max_seqs, prefill_buckets=prefill_buckets,
                donate=donate, prefix_cache=prefix_cache)
            self.draft_meta = dloaded.meta
        self.batcher = ContinuousBatcher(
            self.session, policy, replica=self.idx,
            on_error=self._on_step_error,
            draft_session=self.draft_session)

    @property
    def alive(self) -> bool:
        return self.batcher.alive

    def warmup(self) -> None:
        """Compile the smallest program of every family this replica
        can reach before the port binds."""
        self.session.warmup()
        if int(self.batcher.policy.prefill_batch) > 1:
            # occupancy varies run to run: every (n_seqs, token)
            # bucket pair must be hot or the first odd-sized batch
            # recompiles mid-serving
            self.session.warmup_prefill_batch()
        if self.draft_session is not None:
            k = int(self.batcher.policy.speculate_k)
            self.session.warmup_spec(k, "target")
            self.draft_session.warmup()
            if int(self.batcher.policy.prefill_batch) > 1:
                self.draft_session.warmup_prefill_batch()
            self.draft_session.warmup_spec(k, "draft")

    def generate(self, prompt, max_new: int | None = None):
        return self.batcher.generate(prompt, max_new)

    def generate_adopted(self, manifest: dict, k, v,
                         max_new: int | None = None):
        return self.batcher.generate_adopted(manifest, k, v, max_new)

    def drain_migrate(self) -> None:
        """Scale-down hand-off: see ContinuousBatcher.drain_migrate."""
        self.batcher.drain_migrate()

    def swap(self, version: int, params, model_state=None) -> None:
        self.session.swap(version, params, model_state)

    def swap_draft(self, version: int, params) -> bool:
        """Hot-swap draft weights (the reload watcher's draft poll);
        monotonic like every session swap.  Draft K/V already cached
        was computed by the old draft — still fine: draft caches only
        bias PROPOSALS, and every proposal is verified by the target.
        Returns False when this replica no longer speculates (a failed
        draft restart downgraded it) so the watcher can report
        honestly instead of logging a swap that reached nobody."""
        if self.draft_session is None:
            return False
        self.draft_session.swap(version, params)
        return True

    def _on_step_error(self, exc: BaseException) -> bool:
        from theanompi_tpu.serving.export import load_export

        self.restarts += 1
        monitor.inc("serving/replica_restarts_total", replica=self.idx)
        if self.restarts > self.max_restarts:
            print(f"[decode] replica {self.idx} exhausted "
                  f"{self.max_restarts} restarts "
                  f"({type(exc).__name__}: {exc}); marking it lost",
                  flush=True)
            return False
        try:
            # the version BEING SERVED, not the newest publish: a
            # restart must never become a side door past the reload
            # watcher's IncompatibleExport refusal (serving/server.py
            # Replica._on_batch_error has the same pin)
            loaded = load_export(self.export_dir,
                                 version=self.session.version)
        except Exception as e:
            print(f"[decode] replica {self.idx} restart-from-export "
                  f"failed ({type(e).__name__}: {e}); marking it lost",
                  flush=True)
            return False
        self.session.swap(loaded.version, loaded.params)
        # the failed step may have consumed the donated pool buffers —
        # restart on fresh pages (active sequences were already failed)
        self.session.reset_cache()
        if self.draft_session is not None:
            try:
                dloaded = load_export(self.draft_export_dir,
                                      version=self.draft_session.version)
                self.draft_session.swap(dloaded.version, dloaded.params)
                self.draft_session.reset_cache()
            except Exception as e:
                # the draft is an accelerator, not a dependency: a
                # failed draft reload costs speculation, never the
                # replica (runs on the scheduler thread, like every
                # cache mutation)
                print(f"[decode] replica {self.idx} draft restart "
                      f"failed ({type(e).__name__}: {e}); speculation "
                      "disabled, replica keeps serving", flush=True)
                self.batcher.disable_speculation()
                self.draft_session = None
        print(f"[decode] replica {self.idx} restarted from export "
              f"v{loaded.version} after {type(exc).__name__} "
              f"(restart {self.restarts}/{self.max_restarts})",
              flush=True)
        return True
