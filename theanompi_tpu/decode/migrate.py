"""KV-page migration contract — manifests + the typed refusal.

Disaggregated serving (``theanompi_tpu/frontdoor``) splits the two
phases of one generation stream across processes: a PREFILL replica
runs the compute-bound prompt pass, then the filled KV pages travel to
a DECODE replica as raw wire-v2 frames and the stream continues there
token by token.  The bytes on the wire are just the sequence's slice
of the page pool — ``(n_layers, pages_per_seq, page_size, n_heads,
d_head)`` per pool, the exact ring layout ``DecodeSession._prefill_fn``
scattered — so adoption on the receiver is one fixed-shape scatter
(``DecodeSession.adopt_pages``) and steady state stays zero-recompile.

That only works when both ends agree on the pool geometry, which is
what the **page manifest** pins: every geometry field of the sender's
:class:`~theanompi_tpu.decode.kvcache.CacheConfig` plus the stream's
position state (prompt, length, the first generated token).  The
receiver validates the manifest AND the arrays against its own config
before touching its pool; any mismatch raises the typed
:class:`IncompatiblePages` — a REFUSAL that rides the wire's ``err``
prefix like ``Overloaded``/``IncompatibleExport``, fails only that
stream, and leaves the replica and the connection serving
(tests/test_frontdoor.py pins the whole matrix).

Model-version skew between sender and receiver is tolerated, not
refused: hot reload already lets an in-flight sequence continue on
newer weights (docs/SERVING.md decode reload note), and migration is
the same situation with the phases in different processes.  The
manifest carries the sender's version purely for observability.
"""

from __future__ import annotations

import numpy as np

from theanompi_tpu.decode.kvcache import CacheConfig

#: manifest fields that must equal the receiver's CacheConfig field of
#: the same name — the pool-geometry contract
GEOMETRY_FIELDS = ("n_layers", "n_heads", "d_head", "page_size",
                   "pages_per_seq", "dtype")


class IncompatiblePages(RuntimeError):
    """Migrated KV pages refused: the manifest (or the page arrays
    themselves) do not fit the receiving replica's cache geometry.
    Typed so it rides the RPC ``err`` prefix and the client re-raises
    it as itself — a per-stream refusal, never a replica failure."""


def page_manifest(cfg: CacheConfig, prompt, length: int,
                  first_token: int, version: int = 0) -> dict:
    """The sender-side description of one prefilled stream's pages.

    ``prompt`` is carried whole — it is the router's failover seed (a
    dead decode replica means re-prefilling from the prompt) and the
    receiver's prefix-cache key; ``first_token`` is the prefill
    logits' argmax, emitted by the receiver so the adopted stream's
    output is byte-identical to a local admit.
    """
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    return {
        "n_layers": int(cfg.n_layers),
        "n_heads": int(cfg.n_heads),
        "d_head": int(cfg.d_head),
        "page_size": int(cfg.page_size),
        "pages_per_seq": int(cfg.pages_per_seq),
        "dtype": str(cfg.dtype),
        "length": int(length),
        "prompt": [int(t) for t in prompt],
        "first_token": int(first_token),
        "version": int(version),
    }


def manifest_incompatibility(manifest: dict,
                             cfg: CacheConfig) -> str | None:
    """Why ``manifest`` cannot be adopted into a pool shaped by
    ``cfg`` — None when compatible.  Pure check, shared by the session
    (before allocating pages) and tests (the refusal matrix)."""
    if not isinstance(manifest, dict):
        return f"manifest is {type(manifest).__name__}, not a dict"
    for f in (*GEOMETRY_FIELDS, "length", "prompt", "first_token"):
        if f not in manifest:
            return f"manifest missing field {f!r}"
    for f in GEOMETRY_FIELDS:
        want = getattr(cfg, f)
        got = manifest[f]
        if (str(got) if f == "dtype" else int(got)) != \
                (str(want) if f == "dtype" else int(want)):
            return (f"page geometry mismatch on {f}: sender {got!r} "
                    f"vs receiver {want!r}")
    length = int(manifest["length"])
    if length < 1:
        return f"manifest length {length} < 1"
    if len(manifest["prompt"]) != length:
        return (f"manifest prompt has {len(manifest['prompt'])} "
                f"tokens but length says {length}")
    return None


def pages_incompatibility(manifest: dict, k: np.ndarray, v: np.ndarray,
                          cfg: CacheConfig) -> str | None:
    """Full receiver-side check: the manifest against ``cfg`` AND the
    page arrays against the shape/dtype the manifest promises (a
    manifest can lie — the arrays travel as separate raw frames)."""
    reason = manifest_incompatibility(manifest, cfg)
    if reason is not None:
        return reason
    shape = (cfg.n_layers, cfg.pages_per_seq, cfg.page_size,
             cfg.n_heads, cfg.d_head)
    for name, arr in (("k", k), ("v", v)):
        arr = np.asarray(arr)
        if tuple(arr.shape) != shape:
            return (f"{name} pages shaped {tuple(arr.shape)}, "
                    f"receiver pool wants {shape}")
        if str(arr.dtype) != str(np.dtype(cfg.dtype)):
            return (f"{name} pages dtype {arr.dtype}, receiver pool "
                    f"wants {np.dtype(cfg.dtype)}")
    return None
