"""Fleet-wide prefix cache — the prefill fleet as cache authority.

A replica's :class:`~theanompi_tpu.decode.kvcache.PrefixCache` only
shares prefixes WITHIN its own pool; across the fleet, the same system
prompt still prefills once per replica.  This module closes that gap:
one prefill replica (the AUTHORITY — replica 0 of the prefill role
group, see ``frontdoor/fleet.py``) answers three ops over the ordinary
RPC substrate, and every other replica — prefill peers and decode
replicas alike — attaches a :class:`FleetCacheClient` to its session
(``DecodeSession.fleet``):

* ``cache_lookup(prompt)`` — longest page-aligned prefix the authority
  holds.  A hit INCREFS the entry's pages under a **lease** and ships
  their bytes as raw wire-v2 frames with a geometry manifest (the
  migration contract of ``decode/migrate.py``, minus stream state), so
  remote LRU eviction can never free a page mid-flight: the lease's
  reference keeps it allocated until the reader decrefs.
* ``cache_decref(lease_id)`` — drop the lease once the shipped bytes
  are adopted (or discarded).  An unknown lease — foreign, expired, or
  already released — raises the typed :class:`LeaseError`, which rides
  the wire's ``err`` prefix like ``Overloaded``: the refusal matrix in
  tests/test_frontdoor.py pins foreign-lease / double-decref /
  evict-while-leased.
* ``cache_register(manifest, pages)`` — a replica that just COLD-
  prefilled a prompt offers its longest page-aligned prefix so the
  NEXT replica to see that prompt hits.  The authority validates
  geometry (typed ``IncompatiblePages`` refusal) and adopts the bytes
  as pure cache content (``DecodeSession.adopt_prefix``).

Trust model: the fleet shares one HMAC authkey (the service-key
discipline every plane uses), so a registered prefix is as trusted as
a migrated stream — the authority still validates shape/dtype/geometry
before its pool is touched, and exact-match byte keys mean a poisoned
ENTRY could only ever be served for the exact prompt bytes that
registered it.

The client side is deliberately BEST-EFFORT: a fleet-cache transport
failure counts (``decode/fleet_cache_errors_total``) and degrades to a
local miss — admission never fails because the authority is down.
"""

from __future__ import annotations

import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.decode.migrate import (
    GEOMETRY_FIELDS,
    IncompatiblePages,
    manifest_incompatibility,
)
from theanompi_tpu.parallel import wire
from theanompi_tpu.parallel.service import ServiceClient, ServiceError


class LeaseError(RuntimeError):
    """Typed lease refusal: decref of a lease the authority does not
    hold (foreign id, double decref, or a lease that never existed).
    Rides the RPC ``err`` prefix and re-raises as itself client-side —
    a per-call refusal, the authority keeps serving."""


def prefix_manifest(cfg, prefix, version: int = 0) -> dict:
    """Geometry manifest for shipped PREFIX pages — the
    ``page_manifest`` contract minus stream state (no length /
    first_token: a prefix is cache content, not a live stream).
    ``prefix`` must be page-aligned; its pages travel alongside as raw
    frames shaped ``(n_layers, n_tokens/page_size, page_size, n_heads,
    d_head)`` per pool."""
    prefix = np.asarray(prefix, np.int32).reshape(-1)
    return {
        "n_layers": int(cfg.n_layers),
        "n_heads": int(cfg.n_heads),
        "d_head": int(cfg.d_head),
        "page_size": int(cfg.page_size),
        "pages_per_seq": int(cfg.pages_per_seq),
        "dtype": str(cfg.dtype),
        "n_tokens": int(prefix.shape[0]),
        "prefix": [int(t) for t in prefix],
        "version": int(version),
    }


def prefix_incompatibility(manifest: dict, k, v, cfg) -> str | None:
    """Why shipped prefix pages cannot enter a pool shaped by ``cfg``
    — None when compatible.  Pure check, shared by the authority
    (before register touches its pool), the fetching replica (before
    adopt), and the refusal-matrix tests."""
    if not isinstance(manifest, dict):
        return f"manifest is {type(manifest).__name__}, not a dict"
    for f in (*GEOMETRY_FIELDS, "n_tokens", "prefix"):
        if f not in manifest:
            return f"prefix manifest missing field {f!r}"
    for f in GEOMETRY_FIELDS:
        want = getattr(cfg, f)
        got = manifest[f]
        if (str(got) if f == "dtype" else int(got)) != \
                (str(want) if f == "dtype" else int(want)):
            return (f"page geometry mismatch on {f}: sender {got!r} "
                    f"vs receiver {want!r}")
    n = int(manifest["n_tokens"])
    ps = int(cfg.page_size)
    if n < ps or n % ps:
        return (f"prefix of {n} tokens is not a whole number of "
                f"{ps}-token pages")
    q = n // ps
    if q > int(cfg.pages_per_seq):
        return (f"prefix spans {q} pages > pages_per_seq "
                f"{cfg.pages_per_seq}")
    if len(manifest["prefix"]) != n:
        return (f"prefix manifest carries {len(manifest['prefix'])} "
                f"tokens but n_tokens says {n}")
    shape = (cfg.n_layers, q, ps, cfg.n_heads, cfg.d_head)
    for name, arr in (("k", k), ("v", v)):
        arr = np.asarray(arr)
        if tuple(arr.shape) != shape:
            return (f"{name} prefix pages shaped {tuple(arr.shape)}, "
                    f"receiver wants {shape}")
        if str(arr.dtype) != str(np.dtype(cfg.dtype)):
            return (f"{name} prefix pages dtype {arr.dtype}, receiver "
                    f"pool wants {np.dtype(cfg.dtype)}")
    return None


class FleetCacheClient(ServiceClient):
    """Wire client for the fleet cache authority.

    The low-level ops (:meth:`lookup` / :meth:`decref` /
    :meth:`register_prefix`) re-raise the typed refusals and propagate
    transport errors — the refusal-matrix tests drive those.  The
    session-facing :meth:`fetch` / :meth:`register` wrappers are what
    ``DecodeSession`` calls on its admission path: best-effort, every
    failure counted and swallowed, because a down authority must read
    as a plain cache miss, never a failed admission.
    """

    #: typed errors that re-raise as themselves off the wire
    _TYPED = {LeaseError.__name__: LeaseError,
              IncompatiblePages.__name__: IncompatiblePages}

    def _call_typed(self, op: str, *args):
        try:
            return self.call(op, *args)
        except ServiceError as e:
            for name, cls in self._TYPED.items():
                if name in str(e):
                    raise cls(str(e)) from None
            raise

    # -- low-level ops --------------------------------------------------

    def lookup(self, prompt):
        """Authority lookup: ``(manifest, k, v, lease_id)`` on a hit
        (the lease holds a page reference until :meth:`decref`), None
        on a miss."""
        out = self._call_typed("cache_lookup",
                               np.asarray(prompt, np.int32))
        if out is None:
            return None
        manifest, pages, lease = out
        k, v = pages          # RawArrays decodes to a plain tuple
        return manifest, k, v, lease

    def decref(self, lease_id: str) -> None:
        self._call_typed("cache_decref", str(lease_id))

    def register_prefix(self, manifest: dict, k, v) -> dict:
        return self._call_typed("cache_register", manifest,
                                wire.RawArrays(np.asarray(k),
                                               np.asarray(v)))

    # -- session-facing best-effort wrappers ----------------------------

    def fetch(self, session, prompt) -> bool:
        """On a LOCAL miss: ask the authority, adopt a hit's shipped
        pages into ``session``'s prefix cache.  Returns True when an
        adoption happened (the caller re-resolves locally).  The lease
        is released in ``finally`` — adopted or not, the authority's
        page reference never outlives this call."""
        try:
            got = self.lookup(prompt)
        except Exception:
            monitor.inc("decode/fleet_cache_errors_total")
            return False
        if got is None:
            monitor.inc("decode/fleet_cache_misses_total")
            return False
        manifest, k, v, lease = got
        try:
            reason = prefix_incompatibility(manifest, k, v, session.cfg)
            if reason is not None:
                monitor.inc("decode/fleet_cache_errors_total")
                return False
            adopted = session.adopt_prefix(
                np.asarray(manifest["prefix"], np.int32), k, v)
            if adopted:
                monitor.inc("decode/fleet_cache_hits_total")
                monitor.inc("decode/fleet_cache_ship_bytes_total",
                            float(np.asarray(k).nbytes
                                  + np.asarray(v).nbytes))
            return adopted
        finally:
            try:
                self.decref(lease)
            except Exception:
                monitor.inc("decode/fleet_cache_errors_total")

    def register(self, session, prefix, pages) -> None:
        """Offer a just-prefilled page-aligned prefix (page ids in
        ``session``'s pool) to the authority.  Best effort — errors
        are counted, never raised."""
        try:
            k, v = session.export_page_ids(pages)
            manifest = prefix_manifest(session.cfg, prefix,
                                       version=session.version)
            self.register_prefix(manifest, k, v)
            monitor.inc("decode/fleet_cache_registers_total")
        except Exception:
            monitor.inc("decode/fleet_cache_errors_total")
