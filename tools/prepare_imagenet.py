"""Offline ImageNet preparation CLI — raw image tree -> npz shards.

The rebuild's analogue of the reference's hickle preprocessing scripts
(SURVEY.md §2.9; mount empty, no file:line):

    python tools/prepare_imagenet.py /data/imagenet/train out/ \
        --prefix train --store 256 --shard-size 1024
    python tools/prepare_imagenet.py /data/imagenet/val out/ \
        --prefix val --classes out/classes.json

Expects the ImageFolder layout (<src>/<class>/<img>.jpeg).  Pass the
train run's ``classes.json`` to the val run so labels agree.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("src_dir", help="raw image tree (<src>/<class>/*.jpeg)")
    ap.add_argument("out_dir", help="shard output directory")
    ap.add_argument("--prefix", default="train", choices=("train", "val"))
    ap.add_argument("--store", type=int, default=256,
                    help="stored image side (resize shorter side + center "
                         "crop); training crops store->crop on the fly")
    ap.add_argument("--shard-size", type=int, default=1024)
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--classes", default=None,
                    help="classes.json from a previous run (use the train "
                         "run's mapping for val)")
    ap.add_argument("--no-shuffle", action="store_true",
                    help="keep directory order (default: one global "
                         "shuffle so shards are class-mixed)")
    ap.add_argument("--format", default="npy", choices=("npy", "npz"),
                    dest="shard_format",
                    help="npy (default): mmap-able .x.npy/.y.npy pairs "
                         "— zero-decode training reads; npz: the "
                         "round-1/2 zip container")
    args = ap.parse_args(argv)

    from theanompi_tpu.data.imagenet import prepare_imagenet_from_images

    class_to_idx = None
    if args.classes:
        with open(args.classes) as fh:
            class_to_idx = json.load(fh)
    t0 = time.monotonic()
    paths = prepare_imagenet_from_images(
        args.src_dir, args.out_dir, prefix=args.prefix, store=args.store,
        shard_size=args.shard_size, class_to_idx=class_to_idx,
        workers=args.workers,
        shuffle_seed=None if args.no_shuffle else 0,
        shard_format=args.shard_format)
    dt = time.monotonic() - t0
    print(f"wrote {len(paths)} {args.prefix} shards to {args.out_dir} "
          f"in {dt:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
