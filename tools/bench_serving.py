"""Serving load generator — closed- and open-loop, against a live
server or a self-contained in-process one.

Closed loop (``--mode closed``): N client threads each send
back-to-back requests for ``--duration`` seconds — measures the
server's saturated throughput and the latency it buys (more clients →
bigger coalesced batches → higher throughput per accelerator step).

Open loop (``--mode open``): requests arrive on a Poisson clock at
``--rate`` req/s regardless of completions — the honest
heavy-traffic model (arrivals don't wait for the server), so latency
includes queueing and the admission controller's ``Overloaded``
rejections are counted instead of letting the queue grow without
bound.

Decode (``--decode``): requests are token-generation streams against
a ``tmlocal SERVE --decode`` server (theanompi_tpu/decode).  The
headline numbers change axis: **tokens/s/chip** (the same accounting
as tools/bench_lm.py — utils/token_accounting.py) and **inter-token
latency p50/p99** from the server's own per-token histogram, measured
under overload when the open-loop rate exceeds capacity.  The smoke
artifact lives at ``artifacts/BENCH_decode_smoke.json``.

Prompt-heavy trace (``--decode --mode trace``): S streams whose
prompts share a ``--shared-prefix``-token system prefix and append
long-tail suffixes (``--tail-lengths``), each generating
``--gen-tokens`` — the workload the two token-throughput multipliers
exist for.  Reports **per-stream tok/s** (tokens / that stream's own
wall, queue included) and the server's accept-rate / prefix-cache
counters.  ``--spec-compare`` runs the SAME trace twice on fresh
in-process servers — baseline (no draft, prefix cache off) vs
optimized (speculative decoding + prefix cache) — verifies the two
legs' outputs are byte-identical, and emits one JSON with both legs
plus the per-stream speedup (committed:
``artifacts/BENCH_decode_spec.json``).  The demo draft is the target
re-exported at bf16 (self-speculation: same argmax almost always, so
it measures the accept machinery honestly; a real deployment exports
a separately trained smaller draft).

Mixed trace (``--decode --mode mixed-trace``): the disaggregation
workload — open-loop SHORT chat streams (Poisson at ``--rate``) with
periodic LONG-prompt arrivals (``--long-every-s``) whose prefill is
compute-bound.  Four legs on fresh in-process servers with IDENTICAL
decode capacity: single-role short-only (its baseline), single-role
mixed (the long prefills run between decode steps of the one shared
loop and stall every live stream), disaggregated short-only and
disaggregated mixed (prefill fleet + router + decode fleet — the
decode replica only ever executes cheap adopt scatters).  Headline:
short-stream **inter-token p99** per leg, from the decode server's
own histogram (reset after the warm pass), plus the two ratios the
acceptance pins — single-role mixed blows its baseline up, the
disaggregated fleet holds ~1x.  ``--scale-drill`` appends a REAL
``DisaggregatedFleet`` (subprocess roles, autoscaler on) driven past
the prefill admission bound until scale-up fires, and records the
executed scale events + zero dropped streams; the run's monitor JSONL
lands in ``--monitor-dir``.  The smoke artifact lives at
``artifacts/BENCH_disagg_smoke.json``.

Emits one ``BENCH_serving`` JSON (throughput, latency p50/p95/p99,
batch occupancy / decode sharing from the server's own stats, overload
counts) to ``--out`` and prints it — same artifact discipline as the
other bench tools.

Usage:
    # against a running server (tmlocal SERVE ...):
    python tools/bench_serving.py --addr host:45900 --mode open --rate 200

    # self-contained (exports a tiny model, serves in-process, drives it):
    JAX_PLATFORMS=cpu python tools/bench_serving.py --demo --mode closed

    # token-throughput mode against a decode server (or --demo):
    JAX_PLATFORMS=cpu python tools/bench_serving.py --demo --decode \
        --mode open --rate 20 --gen-tokens 16
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _bootstrap  # noqa: F401,E402  (makes JAX_PLATFORMS effective)
import numpy as np  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _percentiles(ms: list[float]) -> dict:
    if not ms:
        return {}
    a = np.sort(np.asarray(ms))
    pick = lambda q: float(a[min(len(a) - 1, int(q * len(a)))])
    return {"mean": float(a.mean()), "p50": pick(0.50),
            "p95": pick(0.95), "p99": pick(0.99), "max": float(a[-1])}


def _demo_export(tmp_dir: str, decode: bool = False,
                 d_model: int = 32, n_layers: int = 2,
                 n_heads: int = 2, vocab: int = 64,
                 seq_len: int = 32, draft: str | None = None):
    """Export an untrained tiny model so the tool runs anywhere:
    TinyCifar for eval mode, a small TransformerLM for --decode
    (dims CLI-sized so the trace mode can make prefill compute-bound
    on the CPU box).  ``draft='bf16'`` additionally exports the same
    net quantized as the speculative draft (self-speculation) and
    returns (export_dir, draft_dir)."""
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.serving import export_model

    if decode:
        from theanompi_tpu.models.transformer import TransformerLM

        cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                          compute_dtype="float32", optimizer="adamw",
                          learning_rate=1e-3, weight_decay=0.0,
                          lr_schedule="constant")
        model = TransformerLM(config=cfg, vocab=vocab, seq_len=seq_len,
                              n_layers=n_layers, d_model=d_model,
                              n_heads=n_heads, verbose=False)
    else:
        from tests._tiny_models import TinyCifar

        model = TinyCifar(config=ModelConfig(batch_size=8, n_epochs=1,
                                             print_freq=0),
                          verbose=False)
    export_dir = os.path.join(tmp_dir, "export")
    export_model(model, export_dir, version=0)
    if not draft:
        return export_dir
    draft_dir = os.path.join(tmp_dir, "draft")
    export_model(model, draft_dir, version=0, weight_dtype="bf16")
    return export_dir, draft_dir


def _demo_trained_exports(tmp_dir: str, args):
    """Target + genuinely-smaller-draft demo exports for the trace
    mode's honest configuration: BOTH nets train
    ``--demo-train-epochs`` epochs on the synthetic successor-table
    LM task (data/lm.py, noise=0.15 so each learns a Markov rule
    robust to off-chain context) — after which the small draft agrees
    with the target on greedy rollouts because both learned the same
    table, which is exactly the regime speculative decoding is for.
    Returns (export_dir, draft_dir)."""
    from theanompi_tpu.data.lm import SeqLM_data
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.transformer import TransformerLM
    from theanompi_tpu.rules.bsp import run_bsp_session
    from theanompi_tpu.serving import export_model

    def build(d_model, n_layers, n_heads):
        cfg = ModelConfig(batch_size=16,
                          n_epochs=args.demo_train_epochs,
                          print_freq=0, compute_dtype="float32",
                          optimizer="adamw", learning_rate=3e-3,
                          weight_decay=0.0, lr_schedule="constant")
        data = SeqLM_data(vocab=args.demo_vocab,
                          seq_len=args.demo_seq_len, n_train=512,
                          n_val=64, seed=0, noise=0.15)
        return TransformerLM(config=cfg, vocab=args.demo_vocab,
                             seq_len=args.demo_seq_len,
                             n_layers=n_layers, d_model=d_model,
                             n_heads=n_heads, verbose=False, data=data)

    target = build(args.demo_d_model, args.demo_layers,
                   args.demo_heads)
    run_bsp_session(target, checkpoint=False)
    draft = build(args.demo_draft_d_model, args.demo_draft_layers,
                  args.demo_draft_heads)
    run_bsp_session(draft, checkpoint=False)
    export_dir = os.path.join(tmp_dir, "export")
    draft_dir = os.path.join(tmp_dir, "draft")
    export_model(target, export_dir, version=0)
    export_model(draft, draft_dir, version=0)
    return export_dir, draft_dir


def make_trace(shared_prefix: int, tail_lengths: list[int],
               streams: int, vocab: int, seed: int = 0) -> list:
    """The prompt-heavy trace: every stream's prompt = one shared
    system prefix + its own long-tail suffix (lengths cycled from
    ``tail_lengths``).  Deterministic, so compare legs replay
    byte-identical prompts."""
    rng = np.random.default_rng(seed)
    top = max(2, vocab - 1)
    prefix = (rng.integers(0, top, shared_prefix).astype(np.int32) + 1
              if shared_prefix else np.zeros((0,), np.int32))
    prompts = []
    for i in range(streams):
        tail = rng.integers(0, top,
                            tail_lengths[i % len(tail_lengths)])
        prompts.append(np.concatenate(
            [prefix, tail.astype(np.int32) + 1]))
    return prompts


def run_trace(addr: str, prompts: list, gen_tokens: int,
              concurrency: int) -> dict:
    """Drive one stream per prompt (own connection each — the server's
    admission bound, not a client pool, is what saturates), at most
    ``concurrency`` in flight.  Per-stream wall includes queueing —
    the number a user's stream actually experiences."""
    from theanompi_tpu.serving import InferenceClient, Overloaded

    sem = threading.Semaphore(concurrency)
    lock = threading.Lock()
    streams: list[dict | None] = [None] * len(prompts)
    counts = {"ok": 0, "overloaded": 0, "errors": 0}

    def one(i: int) -> None:
        with sem:
            t0 = time.monotonic()
            client = InferenceClient(addr)
            try:
                out = client.generate(prompts[i], gen_tokens)
            except Overloaded:
                with lock:
                    counts["overloaded"] += 1
                return
            except Exception:
                with lock:
                    counts["errors"] += 1
                return
            finally:
                client.close()
            wall = time.monotonic() - t0
            with lock:
                counts["ok"] += 1
                streams[i] = {"wall_s": wall, "tokens": len(out),
                              "prompt_tokens": int(prompts[i].shape[0]),
                              "out": [int(t) for t in out]}

    t_start = time.monotonic()
    threads = [threading.Thread(target=one, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    done = [s for s in streams if s is not None]
    per_stream = [s["tokens"] / s["wall_s"] for s in done
                  if s["wall_s"] > 0]
    return {
        "wall_s": wall,
        "streams": streams,
        "tokens": sum(s["tokens"] for s in done),
        "tok_s_per_stream": {
            "mean": float(np.mean(per_stream)) if per_stream else 0.0,
            "p50": float(np.median(per_stream)) if per_stream else 0.0,
            "min": float(np.min(per_stream)) if per_stream else 0.0,
            "max": float(np.max(per_stream)) if per_stream else 0.0,
        },
        **counts,
    }


def run_load(addr: str, sample: np.ndarray, mode: str, clients: int,
             rate: float, duration: float, decode: bool = False,
             gen_tokens: int = 16) -> dict:
    from theanompi_tpu.serving import InferenceClient, Overloaded

    lock = threading.Lock()
    lat_ms: list[float] = []
    counts = {"ok": 0, "overloaded": 0, "errors": 0, "tokens": 0}

    def one(client) -> None:
        t0 = time.monotonic()
        try:
            if decode:
                out = client.generate(sample, gen_tokens)
            else:
                client.infer(sample)
                out = None
        except Overloaded:
            with lock:
                counts["overloaded"] += 1
            return
        except Exception:
            with lock:
                counts["errors"] += 1
            return
        dt = (time.monotonic() - t0) * 1e3
        with lock:
            counts["ok"] += 1
            if out is not None:
                counts["tokens"] += len(out)
            lat_ms.append(dt)

    t_start = time.monotonic()
    if mode == "closed":
        def worker():
            client = InferenceClient(addr)
            while time.monotonic() - t_start < duration:
                one(client)
            client.close()

        threads = [threading.Thread(target=worker)
                   for _ in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    else:  # open loop: Poisson arrivals, one short-lived thread each
        rng = np.random.default_rng(0)
        # eval requests are ~ms, so a small shared client pool
        # approximates open-loop; a decode STREAM holds its connection
        # for the whole generation (ServiceClient serializes per
        # connection), so every in-flight stream needs its OWN
        # connection or the pool lock — not the server — caps
        # concurrency and the bench measures client queueing
        pool = ([] if decode
                else [InferenceClient(addr) for _ in range(clients)])

        def one_arrival(i: int) -> None:
            if decode:
                c = InferenceClient(addr)
                try:
                    one(c)
                finally:
                    c.close()
            else:
                one(pool[i % clients])

        inflight: list[threading.Thread] = []
        i = 0
        next_t = t_start
        while time.monotonic() - t_start < duration:
            next_t += float(rng.exponential(1.0 / rate))
            delay = next_t - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=one_arrival, args=(i,))
            t.start()
            inflight.append(t)
            i += 1
        for t in inflight:
            t.join()
        for c in pool:
            c.close()
    wall = time.monotonic() - t_start
    return {"wall_s": wall, "latency_ms": _percentiles(lat_ms),
            **counts,
            "throughput_rps": counts["ok"] / wall if wall else 0.0}


def _start_decode_server(export_dir: str, args, draft_dir: str | None,
                         prefix_cache: bool,
                         prefill_batch: int | None = None,
                         prefill_delay_ms: float | None = None):
    from theanompi_tpu.serving import InferenceServer, serve

    decode_opts = dict(
        max_seqs=args.decode_max_seqs,
        max_pending=args.decode_max_pending,
        page_size=args.decode_page_size,
        pages_per_seq=args.decode_pages_per_seq,
        prefix_cache=prefix_cache)
    if prefill_batch is not None:
        decode_opts["prefill_batch"] = int(prefill_batch)
    if prefill_delay_ms is not None:
        decode_opts["prefill_delay_ms"] = float(prefill_delay_ms)
    if args.decode_prefill_buckets:
        decode_opts["prefill_buckets"] = tuple(
            int(b) for b in args.decode_prefill_buckets.split(","))
    if draft_dir:
        decode_opts["draft_export_dir"] = draft_dir
        decode_opts["speculate_k"] = args.speculate_k
    server = InferenceServer(export_dir, replicas=args.replicas,
                             decode=True, decode_opts=decode_opts,
                             reload_poll_s=0).start()
    port = _free_port()
    ready = threading.Event()
    thread = threading.Thread(
        target=serve, args=(server, "127.0.0.1", port, ready),
        daemon=True)
    thread.start()
    assert ready.wait(60), "server never came up"
    return server, thread, f"127.0.0.1:{port}"


def trace_main(args, tmp_dir: str) -> dict:
    """The prompt-heavy trace: one leg honoring the flags, or — with
    ``--spec-compare`` — baseline vs optimized legs on fresh
    in-process servers, byte-identity-checked (module docstring)."""
    from theanompi_tpu.serving import InferenceClient, load_export
    from theanompi_tpu.utils.token_accounting import token_throughput

    export_dir = args.export_dir
    draft_dir = args.draft_export_dir
    if export_dir is None:
        if not args.demo:
            raise SystemExit(
                "--mode trace needs --export-dir or --demo (it "
                "starts its own in-process servers)")
        if args.demo_train_epochs > 0:
            export_dir, draft_dir = _demo_trained_exports(tmp_dir,
                                                          args)
        else:
            export_dir, draft_dir = _demo_export(
                tmp_dir, decode=True, d_model=args.demo_d_model,
                n_layers=args.demo_layers, n_heads=args.demo_heads,
                vocab=args.demo_vocab, seq_len=args.demo_seq_len,
                draft="bf16")
    meta = load_export(export_dir).meta
    vocab = int((meta.get("net") or {}).get("vocab", 64))
    tails = [int(x) for x in args.tail_lengths.split(",")]
    prompts = make_trace(args.shared_prefix, tails, args.streams,
                         vocab)
    if args.spec_compare:
        if draft_dir is None:
            raise SystemExit(
                "--spec-compare needs a draft: pass "
                "--draft-export-dir with --export-dir, or use --demo "
                "(which exports one) — otherwise the 'optimized' leg "
                "would silently run without speculation")
        plan = (("baseline", False, False), ("optimized", True, True))
    else:
        plan = (("trace", bool(draft_dir),
                 not args.no_prefix_cache),)
    legs = {}
    for name, use_draft, use_prefix in plan:
        server, thread, addr = _start_decode_server(
            export_dir, args, draft_dir if use_draft else None,
            use_prefix)
        try:
            probe = InferenceClient(addr)
            # warm pass: compiles every (bucket, family) the trace
            # touches and seeds the prefix cache — the measured pass
            # is the steady state users live in
            run_trace(addr, prompts, args.gen_tokens,
                      args.concurrency)
            warm_compiles = [
                {"target": r.get("compiles"),
                 "draft": r.get("draft_compiles")}
                for r in probe.stats()["replicas"]]
            res = run_trace(addr, prompts, args.gen_tokens,
                            args.concurrency)
            st = probe.stats()
            probe.shutdown()
            probe.close()
        finally:
            server.stop()
            thread.join(timeout=10)
        measured_compiles = [
            {"target": r.get("compiles"),
             "draft": r.get("draft_compiles")}
            for r in st["replicas"]]
        legs[name] = {
            "speculative": use_draft,
            "prefix_cache": use_prefix,
            "tok_s_per_stream": res["tok_s_per_stream"],
            "throughput": token_throughput(res["tokens"],
                                           res["wall_s"]),
            "wall_s": res["wall_s"],
            "ok": res["ok"], "overloaded": res["overloaded"],
            "errors": res["errors"],
            "outputs": [s["out"] if s else None
                        for s in res["streams"]],
            "server": {
                "tokens": st.get("tokens"),
                "steps": st.get("steps"),
                "mean_tokens_per_step": (st["tokens"] / st["steps"]
                                         if st.get("steps") else None),
                "accept_rate": st.get("accept_rate"),
                "prefix_cache_hits": st.get("prefix_cache_hits"),
                "intertoken_ms": (st["replicas"][0] or {}).get(
                    "intertoken_ms"),
            },
            # steady-state pin: the measured pass may not compile
            # anything the warm pass did not
            "zero_steady_state_recompiles":
                warm_compiles == measured_compiles,
            "compiles": measured_compiles,
        }
    out = {
        "bench": "serving",
        "mode": "trace",
        "decode": True,
        "argv": sys.argv[1:],
        "trace": {
            "streams": args.streams,
            "shared_prefix_tokens": args.shared_prefix,
            "tail_lengths": tails,
            "gen_tokens_per_stream": args.gen_tokens,
            "concurrency": args.concurrency,
            "speculate_k": args.speculate_k,
        },
        "model": {"net": meta.get("net"),
                  "weight_dtype": meta.get("weight_dtype")},
        "legs": {name: {k: v for k, v in leg.items()
                        if k != "outputs"}
                 for name, leg in legs.items()},
    }
    if args.spec_compare:
        base, opt = legs["baseline"], legs["optimized"]
        out["byte_identical_output"] = (base["outputs"]
                                        == opt["outputs"])
        b = base["tok_s_per_stream"]["mean"]
        o = opt["tok_s_per_stream"]["mean"]
        out["per_stream_speedup"] = o / b if b else None
    return out


def prefill_compare_main(args, tmp_dir: str) -> dict:
    """``--prefill-compare``: the SAME concurrent prompt trace twice
    on fresh in-process decode servers — serial admission
    (``prefill_batch=1``, byte-for-byte the pre-batching path) vs
    batched admission (``--decode-prefill-batch`` prompts per program
    launch).  Headline: **aggregate prefill tok/s** (prompt tokens /
    prefill program wall, the batcher's own counters) and **TTFT
    p50/p99** from the per-stream time-to-first-token ring, measured
    on a warm second pass.  Verifies both legs' outputs are
    byte-identical and neither compiles anything in the measured pass
    (committed: ``artifacts/BENCH_prefill_batch.json``)."""
    from theanompi_tpu.serving import InferenceClient, load_export

    export_dir = args.export_dir
    if export_dir is None:
        if not args.demo:
            raise SystemExit(
                "--prefill-compare needs --export-dir or --demo (it "
                "starts its own in-process servers)")
        export_dir = _demo_export(
            tmp_dir, decode=True, d_model=args.demo_d_model,
            n_layers=args.demo_layers, n_heads=args.demo_heads,
            vocab=args.demo_vocab, seq_len=args.demo_seq_len)
    meta = load_export(export_dir).meta
    vocab = int((meta.get("net") or {}).get("vocab", 64))
    tails = [int(x) for x in args.tail_lengths.split(",")]
    # DISTINCT prompts (no shared prefix): every admission is a cold
    # prefill, so the measured axis is the program-launch economics of
    # batching itself, not prefix-cache sharing
    prompts = make_trace(0, tails, args.streams, vocab)
    legs = {}
    for name, pb in (("serial", 1),
                     ("batched", args.decode_prefill_batch)):
        print(f"[prefill-compare] leg {name} (prefill_batch={pb}) ...",
              flush=True)
        server, thread, addr = _start_decode_server(
            export_dir, args, None, prefix_cache=True,
            prefill_batch=pb,
            prefill_delay_ms=args.decode_prefill_delay_ms)
        try:
            probe = InferenceClient(addr)
            # warm pass compiles every (n_seqs, token) bucket pair the
            # trace touches; the measured pass is the steady state
            run_trace(addr, prompts, args.gen_tokens,
                      args.concurrency)
            warm_compiles = [r.get("compiles")
                             for r in probe.stats()["replicas"]]
            st0 = probe.stats()
            for r in server.replicas:
                r.batcher.reset_intertoken()
            res = run_trace(addr, prompts, args.gen_tokens,
                            args.concurrency)
            st = probe.stats()
            probe.shutdown()
            probe.close()
        finally:
            server.stop()
            thread.join(timeout=10)
        measured_compiles = [r.get("compiles")
                            for r in st["replicas"]]
        rep, rep0 = st["replicas"][0], st0["replicas"][0]
        pf_tokens = rep["prefill_tokens"] - rep0["prefill_tokens"]
        pf_s = rep["prefill_s"] - rep0["prefill_s"]
        batches = rep["prefill_batches"] - rep0["prefill_batches"]
        legs[name] = {
            "prefill_batch": pb,
            "prefill_delay_ms": args.decode_prefill_delay_ms,
            "ok": res["ok"], "overloaded": res["overloaded"],
            "errors": res["errors"],
            "wall_s": res["wall_s"],
            "outputs": [s["out"] if s else None
                        for s in res["streams"]],
            "prefill": {
                "prompt_tokens": pf_tokens,
                "program_wall_s": pf_s,
                "batches": batches,
                "mean_occupancy": (res["ok"] / batches
                                   if batches else None),
                "max_occupancy": rep["max_prefill_batch"],
                "aggregate_tok_s": pf_tokens / pf_s if pf_s else None,
            },
            "ttft_ms": rep["ttft_ms"],
            "zero_steady_state_recompiles":
                warm_compiles == measured_compiles,
            "compiles": measured_compiles,
        }
    serial, batched = legs["serial"], legs["batched"]
    sp, bp = (serial["prefill"]["aggregate_tok_s"],
              batched["prefill"]["aggregate_tok_s"])
    speedup = bp / sp if sp and bp else None
    s99, b99 = serial["ttft_ms"]["p99"], batched["ttft_ms"]["p99"]
    return {
        "bench": "serving",
        "mode": "prefill-compare",
        "decode": True,
        "argv": sys.argv[1:],
        "trace": {
            "streams": args.streams,
            "tail_lengths": tails,
            "gen_tokens_per_stream": args.gen_tokens,
            "concurrency": args.concurrency,
        },
        "model": {"net": meta.get("net"),
                  "weight_dtype": meta.get("weight_dtype")},
        "legs": {name: {k: v for k, v in leg.items()
                        if k != "outputs"}
                 for name, leg in legs.items()},
        "byte_identical_output": (serial["outputs"]
                                  == batched["outputs"]),
        "aggregate_prefill_speedup": speedup,
        "ttft_p99_ms": {"serial": s99, "batched": b99},
        "acceptance": {
            "aggregate_prefill_2x": (speedup is not None
                                     and speedup >= 2.0),
            "ttft_p99_not_worse": (s99 is not None and b99 is not None
                                   and b99 <= s99),
            "byte_identical_output": (serial["outputs"]
                                      == batched["outputs"]),
            "zero_steady_state_recompiles": (
                serial["zero_steady_state_recompiles"]
                and batched["zero_steady_state_recompiles"]),
        },
    }


def make_mixed_workload(vocab: int, n_short: int, short_tokens: int,
                        long_tokens: int, rate: float,
                        long_every_s: float, seed: int = 0):
    """Deterministic open-loop schedule: ``n_short`` short-chat
    arrivals on a pre-drawn Poisson clock at ``rate`` req/s, plus one
    long-prompt arrival every ``long_every_s`` inside that horizon.
    Every prompt is DISTINCT random tokens (no page-aligned shared
    prefixes → no prefix-cache hits), so the same schedule replays
    byte-comparable prompts across all legs."""
    rng = np.random.default_rng(seed)
    top = max(2, vocab - 1)
    t_short = np.cumsum(rng.exponential(1.0 / rate, n_short))
    shorts = [(float(t_short[i]),
               rng.integers(0, top, short_tokens).astype(np.int32) + 1)
              for i in range(n_short)]
    longs = []
    t = long_every_s
    while t < float(t_short[-1]):
        longs.append((float(t),
                      rng.integers(0, top,
                                   long_tokens).astype(np.int32) + 1))
        t += long_every_s
    return shorts, longs


def run_mixed(make_client, shorts, longs, gen_short: int,
              gen_long: int) -> dict:
    """Replay one mixed schedule open-loop: each arrival gets its own
    thread + connection (streams hold their connection, so the server's
    admission bound — not a client pool — is what saturates).  Returns
    per-class counts and per-arrival outputs (index-aligned with the
    schedule, so legs compare byte-for-byte)."""
    from theanompi_tpu.serving import Overloaded

    lock = threading.Lock()
    out_short: list[dict | None] = [None] * len(shorts)
    out_long: list[dict | None] = [None] * len(longs)
    counts = {"short": {"ok": 0, "overloaded": 0, "errors": 0},
              "long": {"ok": 0, "overloaded": 0, "errors": 0}}

    def one(cls, idx, prompt, gen, sink):
        t0 = time.monotonic()
        client = None
        try:
            client = make_client()
            out = client.generate(prompt, gen)
        except Overloaded:
            with lock:
                counts[cls]["overloaded"] += 1
            return
        except Exception:
            with lock:
                counts[cls]["errors"] += 1
            return
        finally:
            if client is not None:
                try:
                    client.close()
                except Exception:
                    pass
        with lock:
            counts[cls]["ok"] += 1
            sink[idx] = {"wall_s": time.monotonic() - t0,
                         "out": [int(t) for t in out]}

    arrivals = ([("short", i, at, p, gen_short, out_short)
                 for i, (at, p) in enumerate(shorts)]
                + [("long", i, at, p, gen_long, out_long)
                   for i, (at, p) in enumerate(longs)])
    arrivals.sort(key=lambda a: a[2])
    t_start = time.monotonic()
    threads = []
    for cls, idx, at, prompt, gen, sink in arrivals:
        delay = at - (time.monotonic() - t_start)
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=one,
                              args=(cls, idx, prompt, gen, sink))
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    return {
        "wall_s": time.monotonic() - t_start,
        "counts": counts,
        "short_outputs": [s["out"] if s else None for s in out_short],
        "long_outputs": [s["out"] if s else None for s in out_long],
    }


def _measure_mixed_leg(make_client, server, warm_long, warm_shorts,
                       shorts, longs, args) -> dict:
    """Warm pass → drop the decode server's inter-token ring →
    measured replay.  The warm pass must compile every program the
    measured pass can touch: both prompt buckets, AND the decode
    BATCH buckets — those only compile at the concurrency that
    reaches them, so the short warms run ``max_seqs`` wide with
    decaying generation lengths (the active set drains 8→4→2→1
    through every power-of-two bucket)."""
    c = make_client()
    try:
        c.generate(warm_long, args.long_gen_tokens)
    finally:
        c.close()

    def one(prompt, gen):
        cc = make_client()
        try:
            cc.generate(prompt, gen)
        finally:
            cc.close()

    threads = [threading.Thread(target=one, args=(p, g))
               for p, g in warm_shorts]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    warm_compiles = [dict(r.batcher.stats()["compiles"])
                     for r in server.replicas]
    for r in server.replicas:
        r.batcher.reset_intertoken()
    res = run_mixed(make_client, shorts, longs, args.gen_tokens,
                    args.long_gen_tokens)
    measured_compiles = [dict(r.batcher.stats()["compiles"])
                        for r in server.replicas]
    # steady-state pin (same contract as --mode trace): a compile gap
    # in the measured pass would sit in the p99 and lie about physics
    res["zero_steady_state_recompiles"] = (warm_compiles
                                           == measured_compiles)
    return res


def _mixed_leg_summary(res: dict, st: dict) -> dict:
    rep = (st.get("replicas") or [{}])[0] or {}
    return {
        "wall_s": res["wall_s"],
        "counts": res["counts"],
        "zero_steady_state_recompiles":
            res.get("zero_steady_state_recompiles"),
        "intertoken_ms": rep.get("intertoken_ms"),
        "server": {"tokens": st.get("tokens"), "steps": st.get("steps"),
                   "adopted": rep.get("adopted"),
                   "adopt_refused": rep.get("adopt_refused")},
    }


def _outputs_identical(a: list, b: list) -> dict:
    """Index-aligned byte-identity over arrivals that completed in
    BOTH legs (an Overloaded shed in one leg just shrinks the set)."""
    both = [(x, y) for x, y in zip(a, b)
            if x is not None and y is not None]
    return {"identical": bool(both) and all(x == y for x, y in both),
            "compared": len(both)}


def _scale_drill(export_dir: str, args, monitor_dir: str) -> dict:
    """The autoscaler leg, on a REAL subprocess fleet: a tiny prefill
    admission bound (max_pending=2) gets hammered with concurrent
    long-prompt streams until the overload signal trips the
    hysteresis controller and a scale-up executes; then a fresh wave
    must land entirely on the grown fleet — zero errors, zero sheds.
    The whole drill runs under a monitor session rooted at
    ``monitor_dir`` with ``$THEANOMPI_TPU_MONITOR`` exported, so every
    role process ships its metrics JSONL there — the committed
    evidence."""
    from theanompi_tpu import monitor
    from theanompi_tpu.frontdoor.fleet import DisaggregatedFleet
    from theanompi_tpu.frontdoor.router import RouterClient
    from theanompi_tpu.serving import Overloaded

    monitor_dir = os.path.abspath(monitor_dir)
    os.makedirs(monitor_dir, exist_ok=True)
    rng = np.random.default_rng(7)
    top = 63
    long_prompt = lambda: (rng.integers(0, top,
                           args.long_prompt_tokens).astype(np.int32) + 1)
    short_prompt = lambda: (rng.integers(0, top,
                            args.prompt_tokens).astype(np.int32) + 1)
    buckets = (tuple(int(b) for b in
                     args.decode_prefill_buckets.split(","))
               if args.decode_prefill_buckets else None)
    prev_env = os.environ.get(monitor.ENV_VAR)
    os.environ[monitor.ENV_VAR] = monitor_dir  # fan out to children
    try:
        with monitor.session(run_dir=monitor_dir,
                             stall_after=float("inf"),
                             name="bench_frontdoor"):
            monitor.progress(phase="frontdoor")
            with DisaggregatedFleet(
                    export_dir, prefill=1, decode=1,
                    router_host="127.0.0.1",
                    page_size=args.decode_page_size,
                    pages_per_seq=args.decode_pages_per_seq,
                    max_seqs=args.decode_max_seqs,
                    prefill_buckets=buckets,
                    prefill_max_pending=2,
                    decode_max_pending=args.decode_max_pending,
                    autoscale=True, scale_max=2,
                    scale_poll_s=0.5) as fleet:
                addr = fleet.router_addr
                lock = threading.Lock()
                hammer = {"ok": 0, "overloaded": 0, "errors": 0}
                stop = threading.Event()

                def drive():
                    while not stop.is_set():
                        c = None
                        try:
                            c = RouterClient(addr)
                            c.generate(long_prompt(),
                                       args.long_gen_tokens)
                            with lock:
                                hammer["ok"] += 1
                        except Overloaded:
                            with lock:
                                hammer["overloaded"] += 1
                        except Exception:
                            with lock:
                                hammer["errors"] += 1
                        finally:
                            if c is not None:
                                try:
                                    c.close()
                                except Exception:
                                    pass

                drivers = [threading.Thread(target=drive)
                           for _ in range(6)]
                for d in drivers:
                    d.start()
                # wait for the executed scale-up (grow() blocks the
                # autoscaler tick until the new replica answers, so
                # this also covers the replica's JAX warmup)
                deadline = time.monotonic() + 240
                while time.monotonic() < deadline:
                    if fleet.autoscaler.events:
                        break
                    time.sleep(0.25)
                stop.set()
                for d in drivers:
                    d.join()
                events = list(fleet.autoscaler.events)
                # new traffic onto the grown fleet: nothing may drop
                post = {"ok": 0, "overloaded": 0, "errors": 0}

                def wave():
                    c = None
                    try:
                        c = RouterClient(addr)
                        c.generate(long_prompt(), args.long_gen_tokens)
                        c.generate(short_prompt(), args.gen_tokens)
                        with lock:
                            post["ok"] += 1
                    except Overloaded:
                        with lock:
                            post["overloaded"] += 1
                    except Exception:
                        with lock:
                            post["errors"] += 1
                    finally:
                        if c is not None:
                            try:
                                c.close()
                            except Exception:
                                pass

                waves = [threading.Thread(target=wave)
                         for _ in range(4)]
                for w in waves:
                    w.start()
                for w in waves:
                    w.join()
                router_stats = RouterClient(addr).stats()
    finally:
        if prev_env is None:
            os.environ.pop(monitor.ENV_VAR, None)
        else:
            os.environ[monitor.ENV_VAR] = prev_env
    return {
        "monitor_dir": monitor_dir,
        "monitor_files": sorted(os.listdir(monitor_dir)),
        "scale_events": [{"role": r, "direction": d, "addr": a}
                         for r, d, a in events],
        "hammer": hammer,
        "post_scale_wave": post,
        "router": {k: router_stats.get(k)
                   for k in ("streams", "shed", "failovers")},
        "acceptance": {
            "scale_up_executed": any(d == "up" for _, d, _ in events),
            "zero_dropped_streams": (hammer["errors"] == 0
                                     and post["errors"] == 0),
            "post_scale_wave_fully_admitted": (
                post["ok"] == 4 and post["overloaded"] == 0),
        },
    }


def mixed_main(args, tmp_dir: str) -> dict:
    """The disaggregation workload (module docstring): four legs with
    identical decode capacity, short-stream inter-token p99 headline,
    byte-identity across topologies, optional autoscale drill."""
    from theanompi_tpu.frontdoor import router as router_mod
    from theanompi_tpu.frontdoor.autoscale import RoleGroup
    from theanompi_tpu.frontdoor.router import Router, RouterClient
    from theanompi_tpu.serving import InferenceClient, load_export

    export_dir = args.export_dir
    if export_dir is None:
        if not args.demo:
            raise SystemExit(
                "--mode mixed-trace needs --export-dir or --demo (it "
                "starts its own servers and fleets)")
        export_dir = _demo_export(
            tmp_dir, decode=True, d_model=args.demo_d_model,
            n_layers=args.demo_layers, n_heads=args.demo_heads,
            vocab=args.demo_vocab, seq_len=args.demo_seq_len)
    export_dir = os.path.abspath(export_dir)
    meta = load_export(export_dir).meta
    vocab = int((meta.get("net") or {}).get("vocab", 64))
    shorts, longs = make_mixed_workload(
        vocab, args.short_streams, args.prompt_tokens,
        args.long_prompt_tokens, args.rate, args.long_every_s)
    wrng = np.random.default_rng(1234)
    top = max(2, vocab - 1)
    warm_long = (wrng.integers(0, top, args.long_prompt_tokens)
                 .astype(np.int32) + 1)
    warm_shorts = [
        (wrng.integers(0, top, args.prompt_tokens)
         .astype(np.int32) + 1, max(2, 2 * (i + 1)))
        for i in range(args.decode_max_seqs)]

    legs: dict[str, dict] = {}
    outputs: dict[str, dict] = {}

    # -- single-role pair: one decode server does both phases ----------
    for name, leg_longs in (("single_short", []),
                            ("single_mixed", longs)):
        print(f"[mixed-trace] leg {name} ...", flush=True)
        server, sthread, addr = _start_decode_server(
            export_dir, args, None, prefix_cache=True)
        try:
            res = _measure_mixed_leg(
                lambda: InferenceClient(addr), server, warm_long,
                warm_shorts, shorts, leg_longs, args)
            probe = InferenceClient(addr)
            st = probe.stats()
            probe.shutdown()
            probe.close()
        finally:
            server.stop()
            sthread.join(timeout=10)
        legs[name] = _mixed_leg_summary(res, st)
        outputs[name] = {"short": res["short_outputs"],
                         "long": res["long_outputs"]}

    # -- disaggregated pair: the SAME decode server config, prefill
    # offloaded to its own replica process, router in front ------------
    def prefill_argv(port: int) -> list[str]:
        cmd = [sys.executable, "-m", "theanompi_tpu.frontdoor.prefill",
               "--export-dir", export_dir, "--host", "127.0.0.1",
               "--port", str(port),
               "--page-size", str(args.decode_page_size),
               "--pages-per-seq", str(args.decode_pages_per_seq),
               "--max-seqs", str(args.decode_max_seqs),
               "--max-pending", str(args.prefill_max_pending)]
        if args.decode_prefill_buckets:
            cmd += ["--prefill-buckets", args.decode_prefill_buckets]
        if args.prefill_nice and shutil.which("nice"):
            # in production the roles sit on SEPARATE hosts; on a
            # shared CI box the OS timeslices them over the same
            # cores, so a prefill burst would steal cycles from
            # mid-flight decode steps — the exact coupling
            # disaggregation removes.  Deprioritizing the prefill
            # fleet restores the isolation: decode preempts promptly
            # and prefill runs in the gaps (long TTFT pays, short
            # intertoken doesn't — the disaggregation trade, made
            # explicit).  The single-role legs can't be helped this
            # way: their prefill runs INSIDE the decode loop.
            cmd = ["nice", "-n", str(args.prefill_nice)] + cmd
        return cmd

    print("[mixed-trace] booting the prefill replica (subprocess) ...",
          flush=True)
    prefill_group = RoleGroup("prefill", prefill_argv, initial=1)
    try:
        for name, leg_longs in (("disagg_short", []),
                                ("disagg_mixed", longs)):
            print(f"[mixed-trace] leg {name} ...", flush=True)
            server, sthread, decode_addr = _start_decode_server(
                export_dir, args, None, prefix_cache=True)
            router = Router(prefill=prefill_group.addresses(),
                            decode=[decode_addr])
            rport = _free_port()
            ready, rstop = threading.Event(), threading.Event()
            rthread = threading.Thread(
                target=router_mod.serve, daemon=True,
                kwargs=dict(router=router, host="127.0.0.1",
                            port=rport, ready_event=ready,
                            stop_event=rstop))
            rthread.start()
            assert ready.wait(30), "router never came up"
            raddr = f"127.0.0.1:{rport}"
            try:
                res = _measure_mixed_leg(
                    lambda: RouterClient(raddr), server, warm_long,
                    warm_shorts, shorts, leg_longs, args)
                rst = router.stats()
                probe = InferenceClient(decode_addr)
                st = probe.stats()
                probe.shutdown()
                probe.close()
            finally:
                rstop.set()
                rthread.join(timeout=10)
                router.close()
                server.stop()
                sthread.join(timeout=10)
            legs[name] = _mixed_leg_summary(res, st)
            legs[name]["router"] = {k: rst.get(k) for k in
                                    ("streams", "shed", "failovers")}
            outputs[name] = {"short": res["short_outputs"],
                             "long": res["long_outputs"]}
    finally:
        prefill_group.stop()

    p99 = {name: (leg.get("intertoken_ms") or {}).get("p99")
           for name, leg in legs.items()}
    ratio = lambda a, b: (p99[a] / p99[b]
                          if p99.get(a) and p99.get(b) else None)
    ratios = {
        "single_mixed_over_short": ratio("single_mixed",
                                         "single_short"),
        "disagg_mixed_over_short": ratio("disagg_mixed",
                                         "disagg_short"),
    }
    byte_identity = {
        # migration alone (no long-prompt interference) ...
        "disagg_short_vs_single_short": _outputs_identical(
            outputs["disagg_short"]["short"],
            outputs["single_short"]["short"]),
        # ... and under the mixed load, short and long streams both
        "disagg_mixed_vs_single_short": _outputs_identical(
            outputs["disagg_mixed"]["short"],
            outputs["single_short"]["short"]),
        "disagg_mixed_long_vs_single_mixed": _outputs_identical(
            outputs["disagg_mixed"]["long"],
            outputs["single_mixed"]["long"]),
    }
    out = {
        "bench": "serving",
        "mode": "mixed-trace",
        "decode": True,
        "argv": sys.argv[1:],
        "workload": {
            "short_streams": len(shorts),
            "short_prompt_tokens": args.prompt_tokens,
            "short_gen_tokens": args.gen_tokens,
            "long_arrivals": len(longs),
            "long_prompt_tokens": args.long_prompt_tokens,
            "long_gen_tokens": args.long_gen_tokens,
            "rate_rps": args.rate,
            "long_every_s": args.long_every_s,
        },
        "model": {"net": meta.get("net"),
                  "weight_dtype": meta.get("weight_dtype")},
        "legs": legs,
        "intertoken_p99_ms": p99,
        "ratios": ratios,
        "byte_identity": byte_identity,
        "acceptance": {
            "single_role_degrades_3x": (
                ratios["single_mixed_over_short"] is not None
                and ratios["single_mixed_over_short"] >= 3.0),
            "disagg_holds_1p3x": (
                ratios["disagg_mixed_over_short"] is not None
                and ratios["disagg_mixed_over_short"] <= 1.3),
            "byte_identical_migrated_output": all(
                v["identical"] for v in byte_identity.values()),
        },
    }
    if args.scale_drill:
        print("[mixed-trace] scale drill (subprocess fleet, "
              "autoscaler on) ...", flush=True)
        monitor_dir = args.monitor_dir or os.path.join(
            tmp_dir, "monitor")
        out["scale_drill"] = _scale_drill(export_dir, args,
                                          monitor_dir)
    return out


def shm_compare_leg(tmp_dir: str, rounds: int = 10) -> dict:
    """KV-page plane of the shared-memory-lane comparison (ISSUE 20):
    a prefill replica (FRESH subprocess per leg) ships KV pages to the
    client over wire v2 — in-band vs the shm lane — for the SAME
    prompt set, with the k/v page bytes sha256-checked byte-identical
    across legs.  The caller owns the enclosing monitor session (the
    client-side lane counters are registry-global)."""
    import hashlib
    import subprocess

    from theanompi_tpu import monitor
    from theanompi_tpu.frontdoor.prefill import PrefillClient
    from theanompi_tpu.parallel import shm

    export_dir = _demo_export(tmp_dir, decode=True, d_model=64,
                              n_layers=2, n_heads=4, vocab=64,
                              seq_len=64)
    rng = np.random.default_rng(20)
    prompts = [(rng.integers(0, 62, 24).astype(np.int32) + 1)
               for _ in range(4)]
    pre_segments = set(shm.segment_names())
    reg = monitor.registry()
    val = lambda name, **lb: reg.value(name, **lb) or 0.0
    prior = {k: os.environ.get(k) for k in
             ("THEANOMPI_TPU_WIRE_SHM", "THEANOMPI_TPU_SHM_MIN_BYTES")}
    legs: dict[str, dict] = {}
    try:
        # the tiny demo net's KV pages are tens of KB — under the
        # default 64 KiB lane floor; BOTH legs run the same lowered
        # floor so the comparison stays like-for-like
        os.environ["THEANOMPI_TPU_SHM_MIN_BYTES"] = "1024"
        for name, lane in (("in_band", "0"), ("shm", "1")):
            os.environ["THEANOMPI_TPU_WIRE_SHM"] = lane
            port = _free_port()
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 "theanompi_tpu.frontdoor.prefill",
                 "--export-dir", export_dir, "--host", "127.0.0.1",
                 "--port", str(port), "--page-size", "16",
                 "--pages-per-seq", "4", "--max-seqs", "8",
                 "--max-pending", "8", "--prefill-batch", "1",
                 "--prefill-delay-ms", "0", "--platform", "cpu"],
                env=dict(os.environ))
            c = None
            deadline = time.monotonic() + 180
            while c is None:
                try:
                    c = PrefillClient(f"127.0.0.1:{port}")
                    c.ping()
                except Exception:
                    if c is not None:
                        c.close()
                    c = None
                    if proc.poll() is not None:
                        raise RuntimeError(
                            f"prefill replica died (rc={proc.poll()})")
                    if time.monotonic() > deadline:
                        proc.kill()
                        raise RuntimeError(
                            "prefill replica never came up")
                    time.sleep(0.3)
            oob0 = val("shm/oob_bytes_total", dir="recv")
            grants0 = val("shm/grants_total", role="client")
            digest = hashlib.sha256()
            page_bytes = 0
            try:
                for p in prompts:  # warm: prefill program compile
                    c.prefill(p)
                t0 = time.monotonic()
                for _ in range(rounds):
                    for p in prompts:
                        _, k, v = c.prefill(p)
                        digest.update(k.tobytes())
                        digest.update(v.tobytes())
                        page_bytes += k.nbytes + v.nbytes
                wall = time.monotonic() - t0
            finally:
                try:
                    c.shutdown()
                except Exception:
                    pass
                c.close()
                try:
                    proc.wait(timeout=20)
                except Exception:
                    proc.kill()
                    proc.wait(timeout=10)
            n = rounds * len(prompts)
            legs[name] = {
                "prefills": n,
                "wall_s": round(wall, 3),
                "prefill_ms_mean": round(wall / n * 1e3, 2),
                "page_bytes": page_bytes,
                "sha256": digest.hexdigest(),
                "oob_bytes_recv": int(
                    val("shm/oob_bytes_total", dir="recv") - oob0),
                "shm_grants": int(
                    val("shm/grants_total", role="client") - grants0),
            }
            print(f"[bench_serving] shm-compare {name}: "
                  f"{legs[name]['prefill_ms_mean']:.1f} ms/prefill, "
                  f"{legs[name]['oob_bytes_recv']/1e6:.1f} MB "
                  "out-of-band", flush=True)
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    shm.sweep_orphans()
    leaked = [n for n in shm.segment_names() if n not in pre_segments]
    return {
        "plane": "serving_kv",
        "rounds": rounds, "prompts": len(prompts),
        "legs": legs,
        "byte_identical": (legs["shm"]["sha256"]
                           == legs["in_band"]["sha256"]),
        "wall_delta_pct": round(
            100.0 * (1.0 - legs["shm"]["wall_s"]
                     / legs["in_band"]["wall_s"]), 1),
        # page bytes that left the socket path entirely (the client
        # maps them instead of copying them off the wire)
        "socket_bytes_saved": legs["shm"]["oob_bytes_recv"],
        "leaked_segments": len(leaked),
    }


def shm_compare_main(args) -> int:
    """``--shm-compare``: the standalone KV-page shm leg.  Always a
    gate — exits 1 unless the lane carried the pages, the delivered
    bytes are identical to the in-band leg, and nothing leaked."""
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("THEANOMPI_TPU_SERVICE_KEY", "bench-serving")
    from theanompi_tpu import monitor

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.TemporaryDirectory() as td:
        with monitor.session(os.path.join(td, "monitor")):
            doc = shm_compare_leg(td)
    out_doc = {"bench": "serving_shm_lane", **doc}
    path = (args.out if args.out != "BENCH_serving.json"
            else os.path.join(repo, "artifacts",
                              "BENCH_serving_shm.json"))
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(out_doc, f, indent=1)
    print(f"[bench_serving] wrote {path} (shm wall delta "
          f"{doc['wall_delta_pct']:+.1f}%)", flush=True)
    ok = True
    if not doc["byte_identical"]:
        print("[bench_serving] FAIL: shm leg delivered different page "
              "bytes than the in-band leg", file=sys.stderr)
        ok = False
    if doc["legs"]["shm"]["oob_bytes_recv"] <= 0 \
            or doc["legs"]["shm"]["shm_grants"] < 1:
        print("[bench_serving] FAIL: shm leg shows no lane traffic "
              f"({doc['legs']['shm']})", file=sys.stderr)
        ok = False
    if doc["legs"]["in_band"]["oob_bytes_recv"] != 0:
        print("[bench_serving] FAIL: in-band leg leaked lane traffic",
              file=sys.stderr)
        ok = False
    if doc["leaked_segments"]:
        print(f"[bench_serving] FAIL: {doc['leaked_segments']} shm "
              "segment(s) leaked", file=sys.stderr)
        ok = False
    print(f"[bench_serving] shm-compare {'PASS' if ok else 'FAIL'}",
          flush=True)
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--addr", default=None,
                    help="host:port of a running server; omitted = "
                         "serve --export-dir in-process")
    ap.add_argument("--export-dir", default=None)
    ap.add_argument("--demo", action="store_true",
                    help="export an untrained TinyCifar to a temp dir "
                         "first (self-contained CPU run)")
    ap.add_argument("--mode",
                    choices=("closed", "open", "trace", "mixed-trace"),
                    default="closed",
                    help="closed/open loop, 'trace' — the decode "
                         "prompt-heavy trace (shared prefix x many "
                         "streams, per-stream tok/s) — or "
                         "'mixed-trace' — the disaggregation workload "
                         "(open-loop short chat + periodic long "
                         "prompts; single-role vs disaggregated "
                         "inter-token p99)")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--rate", type=float, default=100.0,
                    help="open-loop arrival rate, req/s")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per request")
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=32)
    ap.add_argument("--decode", action="store_true",
                    help="token-throughput mode: drive 'generate' "
                         "streams against a decode server (tokens/s/"
                         "chip + inter-token p50/p99 headline)")
    ap.add_argument("--prompt-tokens", type=int, default=8,
                    help="--decode: prompt length per stream")
    ap.add_argument("--gen-tokens", type=int, default=16,
                    help="--decode: tokens generated per stream")
    ap.add_argument("--decode-max-seqs", type=int, default=8,
                    help="--decode in-process server: max concurrent "
                         "sequences per replica")
    ap.add_argument("--decode-max-pending", type=int, default=32,
                    help="--decode in-process server: admission bound "
                         "(prompts beyond it get Overloaded)")
    ap.add_argument("--decode-page-size", type=int, default=16,
                    help="--decode in-process trace server: tokens "
                         "per KV page")
    ap.add_argument("--decode-pages-per-seq", type=int, default=8,
                    help="--decode in-process trace server: pages per "
                         "sequence (window = page_size x pages)")
    ap.add_argument("--decode-prefill-buckets", default=None,
                    metavar="N,N,...",
                    help="--decode in-process trace server: padded "
                         "prompt-length buckets")
    ap.add_argument("--shared-prefix", type=int, default=0,
                    help="--mode trace: shared system-prefix tokens "
                         "prepended to every stream's prompt")
    ap.add_argument("--tail-lengths", default="1,2,4,8,16",
                    help="--mode trace: long-tail per-stream prompt "
                         "suffix lengths, cycled")
    ap.add_argument("--streams", type=int, default=16,
                    help="--mode trace: generation streams in the "
                         "trace")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="--mode trace: max streams in flight")
    ap.add_argument("--short-streams", type=int, default=40,
                    help="--mode mixed-trace: short-chat arrivals in "
                         "the schedule (prompts = --prompt-tokens, "
                         "generation = --gen-tokens, Poisson at "
                         "--rate)")
    ap.add_argument("--long-prompt-tokens", type=int, default=224,
                    help="--mode mixed-trace: prompt length of the "
                         "periodic long arrivals (the compute-bound "
                         "prefill)")
    ap.add_argument("--long-gen-tokens", type=int, default=2,
                    help="--mode mixed-trace: tokens generated per "
                         "long stream")
    ap.add_argument("--long-every-s", type=float, default=0.5,
                    help="--mode mixed-trace: long-arrival period")
    ap.add_argument("--prefill-max-pending", type=int, default=8,
                    help="--mode mixed-trace: the prefill replica's "
                         "admission bound")
    ap.add_argument("--prefill-nice", type=int, default=5,
                    help="--mode mixed-trace: CPU niceness for the "
                         "prefill subprocess — emulates the separate "
                         "host the prefill role gets in production, "
                         "so a shared CI box's timeslicing doesn't "
                         "charge prefill bursts to decode intertoken "
                         "(0 = share the cores as-is)")
    ap.add_argument("--scale-drill", action="store_true",
                    help="--mode mixed-trace: append the autoscaler "
                         "leg — a real subprocess fleet hammered past "
                         "its prefill admission bound until scale-up "
                         "executes (monitor JSONL lands in "
                         "--monitor-dir)")
    ap.add_argument("--monitor-dir", default=None,
                    help="--scale-drill: directory for the drill's "
                         "monitor metrics JSONL (default: a temp dir, "
                         "i.e. discarded)")
    ap.add_argument("--speculate-k", type=int, default=4,
                    help="draft tokens per speculative round")
    ap.add_argument("--draft-export-dir", default=None,
                    help="speculative draft export for the in-process "
                         "server (--demo exports a bf16 self-draft)")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="--mode trace single leg: disable the prefix "
                         "cache")
    ap.add_argument("--prefill-compare", action="store_true",
                    help="--decode: run the SAME concurrent prompt "
                         "trace on a serial-admission server "
                         "(prefill_batch=1) and a batched one "
                         "(--decode-prefill-batch), verify "
                         "byte-identical outputs, report aggregate "
                         "prefill tok/s + TTFT p50/p99 per leg")
    ap.add_argument("--decode-prefill-batch", type=int, default=8,
                    help="--decode in-process server: prompts "
                         "coalesced into one batched prefill program "
                         "(1 = serial admission)")
    ap.add_argument("--decode-prefill-delay-ms", type=float,
                    default=2.0,
                    help="--decode in-process server: how long the "
                         "oldest pending prompt waits for company "
                         "before a partial batch launches")
    ap.add_argument("--spec-compare", action="store_true",
                    help="--mode trace: run baseline (no draft, no "
                         "prefix cache) and optimized (both on) legs "
                         "over the SAME trace, verify byte-identical "
                         "outputs, report the per-stream speedup")
    ap.add_argument("--demo-d-model", type=int, default=32)
    ap.add_argument("--demo-layers", type=int, default=2)
    ap.add_argument("--demo-heads", type=int, default=2)
    ap.add_argument("--demo-vocab", type=int, default=64)
    ap.add_argument("--demo-seq-len", type=int, default=32)
    ap.add_argument("--demo-train-epochs", type=int, default=0,
                    help="--mode trace --demo: train target AND a "
                         "smaller draft net this many epochs on the "
                         "synthetic successor-table LM task before "
                         "exporting (0 = untrained target with a bf16 "
                         "self-draft)")
    ap.add_argument("--demo-draft-d-model", type=int, default=64)
    ap.add_argument("--demo-draft-layers", type=int, default=1)
    ap.add_argument("--demo-draft-heads", type=int, default=2)
    ap.add_argument("--shm-compare", action="store_true",
                    help="shared-memory-lane leg (ISSUE 20): ship the "
                         "SAME KV pages from a fresh prefill "
                         "subprocess in-band vs over the shm lane, "
                         "byte-identity-checked; exits 1 unless the "
                         "lane carried the pages with zero leaked "
                         "segments")
    ap.add_argument("--out", default="BENCH_serving.json")
    args = ap.parse_args(argv)

    if args.shm_compare:
        return shm_compare_main(args)
    if args.prefill_compare or args.mode in ("trace", "mixed-trace"):
        if not args.decode:
            ap.error("--prefill-compare is a --decode mode"
                     if args.prefill_compare
                     else f"--mode {args.mode} is a --decode mode")
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            out = (prefill_compare_main(args, td)
                   if args.prefill_compare
                   else trace_main(args, td) if args.mode == "trace"
                   else mixed_main(args, td))
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps(out, indent=1))
        print(f"BENCH_serving written to {args.out}")
        return 0

    import tempfile

    from theanompi_tpu.serving import (
        BatchPolicy,
        InferenceClient,
        InferenceServer,
        load_export,
        serve,
    )

    tmp_ctx = tempfile.TemporaryDirectory()
    server = thread = None
    try:
        if args.addr is None:
            export_dir = args.export_dir
            if export_dir is None:
                if not args.demo:
                    ap.error("need --addr, --export-dir, or --demo")
                export_dir = _demo_export(tmp_ctx.name,
                                          decode=args.decode)
            policy = BatchPolicy(max_batch=args.max_batch,
                                 max_delay_ms=args.max_delay_ms,
                                 max_queue=args.max_queue)
            decode_opts = (dict(max_seqs=args.decode_max_seqs,
                                max_pending=args.decode_max_pending)
                           if args.decode else None)
            server = InferenceServer(export_dir,
                                     replicas=args.replicas,
                                     policy=policy,
                                     decode=args.decode,
                                     decode_opts=decode_opts).start()
            port = _free_port()
            ready = threading.Event()
            thread = threading.Thread(
                target=serve, args=(server, "127.0.0.1", port, ready),
                daemon=True)
            thread.start()
            assert ready.wait(30), "server never came up"
            addr = f"127.0.0.1:{port}"
            meta = load_export(export_dir).meta
        else:
            addr = args.addr
            if args.export_dir:
                meta = load_export(args.export_dir).meta
            else:
                meta = {}
        if args.decode:
            vocab = int((meta.get("net") or {}).get("vocab", 64))
            sample = (np.arange(args.prompt_tokens, dtype=np.int32)
                      % max(2, vocab - 1)) + 1
        else:
            shape = tuple(meta.get("sample_shape") or (32, 32, 3))
            dtype = np.dtype(meta.get("sample_dtype") or "uint8")
            sample = np.zeros((args.rows, *shape), dtype)

        probe = InferenceClient(addr)
        if args.decode:  # one warm stream outside the window
            probe.generate(sample, args.gen_tokens)
        else:
            probe.infer(sample)
        result = run_load(addr, sample, args.mode, args.clients,
                          args.rate, args.duration,
                          decode=args.decode,
                          gen_tokens=args.gen_tokens)
        stats = probe.stats()
        if server is not None:
            probe.shutdown()
        probe.close()
        out = {
            "bench": "serving",
            "mode": args.mode,
            "decode": args.decode,
            "clients": args.clients,
            "rate_rps": args.rate if args.mode == "open" else None,
            "server": {
                "addr": addr,
                "version": stats.get("version"),
                "replicas": stats.get("live_replicas"),
                "overloaded": stats.get("overloaded"),
            },
            **result,
        }
        if args.decode:
            # tokens/s accounted identically to training bench_lm.py
            from theanompi_tpu.utils.token_accounting import (
                token_throughput,
            )

            n_chips = 1
            if server is not None:
                import jax

                n_chips = len(jax.devices())
            reps = stats.get("replicas") or [{}]
            out.update(
                prompt_tokens=args.prompt_tokens,
                gen_tokens_per_stream=args.gen_tokens,
                throughput=token_throughput(result["tokens"],
                                            result["wall_s"], n_chips),
                intertoken_ms=reps[0].get("intertoken_ms"),
                server_decode={
                    "tokens": stats.get("tokens"),
                    "steps": stats.get("steps"),
                    "shared_steps": stats.get("shared_steps"),
                    "max_concurrent": stats.get("max_concurrent"),
                    "mean_tokens_per_step": (
                        stats["tokens"] / stats["steps"]
                        if stats.get("steps") else None),
                },
            )
        else:
            out.update(
                rows_per_request=args.rows,
                server_batching={
                    "batches": stats.get("batches"),
                    "batch_rows": stats.get("rows"),
                    "max_occupancy": stats.get("max_occupancy"),
                    "mean_occupancy": (stats["rows"] / stats["batches"]
                                       if stats.get("batches")
                                       else None),
                },
            )
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps(out, indent=1))
        print(f"BENCH_serving written to {args.out}")
        return 0
    finally:
        if server is not None:
            server.stop()
        tmp_ctx.cleanup()


if __name__ == "__main__":
    raise SystemExit(main())
