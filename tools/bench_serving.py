"""Serving load generator — closed- and open-loop, against a live
server or a self-contained in-process one.

Closed loop (``--mode closed``): N client threads each send
back-to-back requests for ``--duration`` seconds — measures the
server's saturated throughput and the latency it buys (more clients →
bigger coalesced batches → higher throughput per accelerator step).

Open loop (``--mode open``): requests arrive on a Poisson clock at
``--rate`` req/s regardless of completions — the honest
heavy-traffic model (arrivals don't wait for the server), so latency
includes queueing and the admission controller's ``Overloaded``
rejections are counted instead of letting the queue grow without
bound.

Emits one ``BENCH_serving`` JSON (throughput, latency p50/p95/p99,
batch occupancy from the server's own stats, overload counts) to
``--out`` and prints it — same artifact discipline as the other bench
tools.

Usage:
    # against a running server (tmlocal SERVE ...):
    python tools/bench_serving.py --addr host:45900 --mode open --rate 200

    # self-contained (exports a tiny model, serves in-process, drives it):
    JAX_PLATFORMS=cpu python tools/bench_serving.py --demo --mode closed
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _bootstrap  # noqa: F401,E402  (makes JAX_PLATFORMS effective)
import numpy as np  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _percentiles(ms: list[float]) -> dict:
    if not ms:
        return {}
    a = np.sort(np.asarray(ms))
    pick = lambda q: float(a[min(len(a) - 1, int(q * len(a)))])
    return {"mean": float(a.mean()), "p50": pick(0.50),
            "p95": pick(0.95), "p99": pick(0.99), "max": float(a[-1])}


def _demo_export(tmp_dir: str) -> str:
    """Export an untrained TinyCifar so the tool runs anywhere."""
    from tests._tiny_models import TinyCifar
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.serving import export_model

    model = TinyCifar(config=ModelConfig(batch_size=8, n_epochs=1,
                                         print_freq=0), verbose=False)
    export_dir = os.path.join(tmp_dir, "export")
    export_model(model, export_dir, version=0)
    return export_dir


def run_load(addr: str, sample: np.ndarray, mode: str, clients: int,
             rate: float, duration: float) -> dict:
    from theanompi_tpu.serving import InferenceClient, Overloaded

    lock = threading.Lock()
    lat_ms: list[float] = []
    counts = {"ok": 0, "overloaded": 0, "errors": 0}

    def one(client) -> None:
        t0 = time.monotonic()
        try:
            client.infer(sample)
        except Overloaded:
            with lock:
                counts["overloaded"] += 1
            return
        except Exception:
            with lock:
                counts["errors"] += 1
            return
        dt = (time.monotonic() - t0) * 1e3
        with lock:
            counts["ok"] += 1
            lat_ms.append(dt)

    t_start = time.monotonic()
    if mode == "closed":
        def worker():
            client = InferenceClient(addr)
            while time.monotonic() - t_start < duration:
                one(client)
            client.close()

        threads = [threading.Thread(target=worker)
                   for _ in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    else:  # open loop: Poisson arrivals, one short-lived thread each
        rng = np.random.default_rng(0)
        pool = [InferenceClient(addr) for _ in range(clients)]
        inflight: list[threading.Thread] = []
        i = 0
        next_t = t_start
        while time.monotonic() - t_start < duration:
            next_t += float(rng.exponential(1.0 / rate))
            delay = next_t - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=one, args=(pool[i % clients],))
            t.start()
            inflight.append(t)
            i += 1
        for t in inflight:
            t.join()
        for c in pool:
            c.close()
    wall = time.monotonic() - t_start
    return {"wall_s": wall, "latency_ms": _percentiles(lat_ms),
            **counts,
            "throughput_rps": counts["ok"] / wall if wall else 0.0}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--addr", default=None,
                    help="host:port of a running server; omitted = "
                         "serve --export-dir in-process")
    ap.add_argument("--export-dir", default=None)
    ap.add_argument("--demo", action="store_true",
                    help="export an untrained TinyCifar to a temp dir "
                         "first (self-contained CPU run)")
    ap.add_argument("--mode", choices=("closed", "open"),
                    default="closed")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--rate", type=float, default=100.0,
                    help="open-loop arrival rate, req/s")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per request")
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=32)
    ap.add_argument("--out", default="BENCH_serving.json")
    args = ap.parse_args(argv)

    import tempfile

    from theanompi_tpu.serving import (
        BatchPolicy,
        InferenceClient,
        InferenceServer,
        load_export,
        serve,
    )

    tmp_ctx = tempfile.TemporaryDirectory()
    server = thread = None
    try:
        if args.addr is None:
            export_dir = args.export_dir
            if export_dir is None:
                if not args.demo:
                    ap.error("need --addr, --export-dir, or --demo")
                export_dir = _demo_export(tmp_ctx.name)
            policy = BatchPolicy(max_batch=args.max_batch,
                                 max_delay_ms=args.max_delay_ms,
                                 max_queue=args.max_queue)
            server = InferenceServer(export_dir,
                                     replicas=args.replicas,
                                     policy=policy).start()
            port = _free_port()
            ready = threading.Event()
            thread = threading.Thread(
                target=serve, args=(server, "127.0.0.1", port, ready),
                daemon=True)
            thread.start()
            assert ready.wait(30), "server never came up"
            addr = f"127.0.0.1:{port}"
            meta = load_export(export_dir).meta
        else:
            addr = args.addr
            if args.export_dir:
                meta = load_export(args.export_dir).meta
            else:
                meta = {}
        shape = tuple(meta.get("sample_shape") or (32, 32, 3))
        dtype = np.dtype(meta.get("sample_dtype") or "uint8")
        sample = np.zeros((args.rows, *shape), dtype)

        probe = InferenceClient(addr)
        probe.infer(sample)  # one warm request outside the window
        result = run_load(addr, sample, args.mode, args.clients,
                          args.rate, args.duration)
        stats = probe.stats()
        if server is not None:
            probe.shutdown()
        probe.close()
        out = {
            "bench": "serving",
            "mode": args.mode,
            "clients": args.clients,
            "rate_rps": args.rate if args.mode == "open" else None,
            "rows_per_request": args.rows,
            "server": {
                "addr": addr,
                "version": stats.get("version"),
                "replicas": stats.get("live_replicas"),
                "batches": stats.get("batches"),
                "batch_rows": stats.get("rows"),
                "max_occupancy": stats.get("max_occupancy"),
                "mean_occupancy": (stats["rows"] / stats["batches"]
                                   if stats.get("batches") else None),
                "overloaded": stats.get("overloaded"),
            },
            **result,
        }
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps(out, indent=1))
        print(f"BENCH_serving written to {args.out}")
        return 0
    finally:
        if server is not None:
            server.stop()
        tmp_ctx.cleanup()


if __name__ == "__main__":
    raise SystemExit(main())
