"""Serving load generator — closed- and open-loop, against a live
server or a self-contained in-process one.

Closed loop (``--mode closed``): N client threads each send
back-to-back requests for ``--duration`` seconds — measures the
server's saturated throughput and the latency it buys (more clients →
bigger coalesced batches → higher throughput per accelerator step).

Open loop (``--mode open``): requests arrive on a Poisson clock at
``--rate`` req/s regardless of completions — the honest
heavy-traffic model (arrivals don't wait for the server), so latency
includes queueing and the admission controller's ``Overloaded``
rejections are counted instead of letting the queue grow without
bound.

Decode (``--decode``): requests are token-generation streams against
a ``tmlocal SERVE --decode`` server (theanompi_tpu/decode).  The
headline numbers change axis: **tokens/s/chip** (the same accounting
as tools/bench_lm.py — utils/token_accounting.py) and **inter-token
latency p50/p99** from the server's own per-token histogram, measured
under overload when the open-loop rate exceeds capacity.  The smoke
artifact lives at ``artifacts/BENCH_decode_smoke.json``.

Emits one ``BENCH_serving`` JSON (throughput, latency p50/p95/p99,
batch occupancy / decode sharing from the server's own stats, overload
counts) to ``--out`` and prints it — same artifact discipline as the
other bench tools.

Usage:
    # against a running server (tmlocal SERVE ...):
    python tools/bench_serving.py --addr host:45900 --mode open --rate 200

    # self-contained (exports a tiny model, serves in-process, drives it):
    JAX_PLATFORMS=cpu python tools/bench_serving.py --demo --mode closed

    # token-throughput mode against a decode server (or --demo):
    JAX_PLATFORMS=cpu python tools/bench_serving.py --demo --decode \
        --mode open --rate 20 --gen-tokens 16
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _bootstrap  # noqa: F401,E402  (makes JAX_PLATFORMS effective)
import numpy as np  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _percentiles(ms: list[float]) -> dict:
    if not ms:
        return {}
    a = np.sort(np.asarray(ms))
    pick = lambda q: float(a[min(len(a) - 1, int(q * len(a)))])
    return {"mean": float(a.mean()), "p50": pick(0.50),
            "p95": pick(0.95), "p99": pick(0.99), "max": float(a[-1])}


def _demo_export(tmp_dir: str, decode: bool = False) -> str:
    """Export an untrained tiny model so the tool runs anywhere:
    TinyCifar for eval mode, a small TransformerLM for --decode."""
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.serving import export_model

    if decode:
        from theanompi_tpu.models.transformer import TransformerLM

        cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                          compute_dtype="float32", optimizer="adamw",
                          learning_rate=1e-3, weight_decay=0.0,
                          lr_schedule="constant")
        model = TransformerLM(config=cfg, vocab=64, seq_len=32,
                              n_layers=2, d_model=32, n_heads=2,
                              verbose=False)
    else:
        from tests._tiny_models import TinyCifar

        model = TinyCifar(config=ModelConfig(batch_size=8, n_epochs=1,
                                             print_freq=0),
                          verbose=False)
    export_dir = os.path.join(tmp_dir, "export")
    export_model(model, export_dir, version=0)
    return export_dir


def run_load(addr: str, sample: np.ndarray, mode: str, clients: int,
             rate: float, duration: float, decode: bool = False,
             gen_tokens: int = 16) -> dict:
    from theanompi_tpu.serving import InferenceClient, Overloaded

    lock = threading.Lock()
    lat_ms: list[float] = []
    counts = {"ok": 0, "overloaded": 0, "errors": 0, "tokens": 0}

    def one(client) -> None:
        t0 = time.monotonic()
        try:
            if decode:
                out = client.generate(sample, gen_tokens)
            else:
                client.infer(sample)
                out = None
        except Overloaded:
            with lock:
                counts["overloaded"] += 1
            return
        except Exception:
            with lock:
                counts["errors"] += 1
            return
        dt = (time.monotonic() - t0) * 1e3
        with lock:
            counts["ok"] += 1
            if out is not None:
                counts["tokens"] += len(out)
            lat_ms.append(dt)

    t_start = time.monotonic()
    if mode == "closed":
        def worker():
            client = InferenceClient(addr)
            while time.monotonic() - t_start < duration:
                one(client)
            client.close()

        threads = [threading.Thread(target=worker)
                   for _ in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    else:  # open loop: Poisson arrivals, one short-lived thread each
        rng = np.random.default_rng(0)
        # eval requests are ~ms, so a small shared client pool
        # approximates open-loop; a decode STREAM holds its connection
        # for the whole generation (ServiceClient serializes per
        # connection), so every in-flight stream needs its OWN
        # connection or the pool lock — not the server — caps
        # concurrency and the bench measures client queueing
        pool = ([] if decode
                else [InferenceClient(addr) for _ in range(clients)])

        def one_arrival(i: int) -> None:
            if decode:
                c = InferenceClient(addr)
                try:
                    one(c)
                finally:
                    c.close()
            else:
                one(pool[i % clients])

        inflight: list[threading.Thread] = []
        i = 0
        next_t = t_start
        while time.monotonic() - t_start < duration:
            next_t += float(rng.exponential(1.0 / rate))
            delay = next_t - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=one_arrival, args=(i,))
            t.start()
            inflight.append(t)
            i += 1
        for t in inflight:
            t.join()
        for c in pool:
            c.close()
    wall = time.monotonic() - t_start
    return {"wall_s": wall, "latency_ms": _percentiles(lat_ms),
            **counts,
            "throughput_rps": counts["ok"] / wall if wall else 0.0}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--addr", default=None,
                    help="host:port of a running server; omitted = "
                         "serve --export-dir in-process")
    ap.add_argument("--export-dir", default=None)
    ap.add_argument("--demo", action="store_true",
                    help="export an untrained TinyCifar to a temp dir "
                         "first (self-contained CPU run)")
    ap.add_argument("--mode", choices=("closed", "open"),
                    default="closed")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--rate", type=float, default=100.0,
                    help="open-loop arrival rate, req/s")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per request")
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=32)
    ap.add_argument("--decode", action="store_true",
                    help="token-throughput mode: drive 'generate' "
                         "streams against a decode server (tokens/s/"
                         "chip + inter-token p50/p99 headline)")
    ap.add_argument("--prompt-tokens", type=int, default=8,
                    help="--decode: prompt length per stream")
    ap.add_argument("--gen-tokens", type=int, default=16,
                    help="--decode: tokens generated per stream")
    ap.add_argument("--decode-max-seqs", type=int, default=8,
                    help="--decode in-process server: max concurrent "
                         "sequences per replica")
    ap.add_argument("--decode-max-pending", type=int, default=32,
                    help="--decode in-process server: admission bound "
                         "(prompts beyond it get Overloaded)")
    ap.add_argument("--out", default="BENCH_serving.json")
    args = ap.parse_args(argv)

    import tempfile

    from theanompi_tpu.serving import (
        BatchPolicy,
        InferenceClient,
        InferenceServer,
        load_export,
        serve,
    )

    tmp_ctx = tempfile.TemporaryDirectory()
    server = thread = None
    try:
        if args.addr is None:
            export_dir = args.export_dir
            if export_dir is None:
                if not args.demo:
                    ap.error("need --addr, --export-dir, or --demo")
                export_dir = _demo_export(tmp_ctx.name,
                                          decode=args.decode)
            policy = BatchPolicy(max_batch=args.max_batch,
                                 max_delay_ms=args.max_delay_ms,
                                 max_queue=args.max_queue)
            decode_opts = (dict(max_seqs=args.decode_max_seqs,
                                max_pending=args.decode_max_pending)
                           if args.decode else None)
            server = InferenceServer(export_dir,
                                     replicas=args.replicas,
                                     policy=policy,
                                     decode=args.decode,
                                     decode_opts=decode_opts).start()
            port = _free_port()
            ready = threading.Event()
            thread = threading.Thread(
                target=serve, args=(server, "127.0.0.1", port, ready),
                daemon=True)
            thread.start()
            assert ready.wait(30), "server never came up"
            addr = f"127.0.0.1:{port}"
            meta = load_export(export_dir).meta
        else:
            addr = args.addr
            if args.export_dir:
                meta = load_export(args.export_dir).meta
            else:
                meta = {}
        if args.decode:
            vocab = int((meta.get("net") or {}).get("vocab", 64))
            sample = (np.arange(args.prompt_tokens, dtype=np.int32)
                      % max(2, vocab - 1)) + 1
        else:
            shape = tuple(meta.get("sample_shape") or (32, 32, 3))
            dtype = np.dtype(meta.get("sample_dtype") or "uint8")
            sample = np.zeros((args.rows, *shape), dtype)

        probe = InferenceClient(addr)
        if args.decode:  # one warm stream outside the window
            probe.generate(sample, args.gen_tokens)
        else:
            probe.infer(sample)
        result = run_load(addr, sample, args.mode, args.clients,
                          args.rate, args.duration,
                          decode=args.decode,
                          gen_tokens=args.gen_tokens)
        stats = probe.stats()
        if server is not None:
            probe.shutdown()
        probe.close()
        out = {
            "bench": "serving",
            "mode": args.mode,
            "decode": args.decode,
            "clients": args.clients,
            "rate_rps": args.rate if args.mode == "open" else None,
            "server": {
                "addr": addr,
                "version": stats.get("version"),
                "replicas": stats.get("live_replicas"),
                "overloaded": stats.get("overloaded"),
            },
            **result,
        }
        if args.decode:
            # tokens/s accounted identically to training bench_lm.py
            from theanompi_tpu.utils.token_accounting import (
                token_throughput,
            )

            n_chips = 1
            if server is not None:
                import jax

                n_chips = len(jax.devices())
            reps = stats.get("replicas") or [{}]
            out.update(
                prompt_tokens=args.prompt_tokens,
                gen_tokens_per_stream=args.gen_tokens,
                throughput=token_throughput(result["tokens"],
                                            result["wall_s"], n_chips),
                intertoken_ms=reps[0].get("intertoken_ms"),
                server_decode={
                    "tokens": stats.get("tokens"),
                    "steps": stats.get("steps"),
                    "shared_steps": stats.get("shared_steps"),
                    "max_concurrent": stats.get("max_concurrent"),
                    "mean_tokens_per_step": (
                        stats["tokens"] / stats["steps"]
                        if stats.get("steps") else None),
                },
            )
        else:
            out.update(
                rows_per_request=args.rows,
                server_batching={
                    "batches": stats.get("batches"),
                    "batch_rows": stats.get("rows"),
                    "max_occupancy": stats.get("max_occupancy"),
                    "mean_occupancy": (stats["rows"] / stats["batches"]
                                       if stats.get("batches")
                                       else None),
                },
            )
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps(out, indent=1))
        print(f"BENCH_serving written to {args.out}")
        return 0
    finally:
        if server is not None:
            server.stop()
        tmp_ctx.cleanup()


if __name__ == "__main__":
    raise SystemExit(main())
