"""Serving load generator — closed- and open-loop, against a live
server or a self-contained in-process one.

Closed loop (``--mode closed``): N client threads each send
back-to-back requests for ``--duration`` seconds — measures the
server's saturated throughput and the latency it buys (more clients →
bigger coalesced batches → higher throughput per accelerator step).

Open loop (``--mode open``): requests arrive on a Poisson clock at
``--rate`` req/s regardless of completions — the honest
heavy-traffic model (arrivals don't wait for the server), so latency
includes queueing and the admission controller's ``Overloaded``
rejections are counted instead of letting the queue grow without
bound.

Decode (``--decode``): requests are token-generation streams against
a ``tmlocal SERVE --decode`` server (theanompi_tpu/decode).  The
headline numbers change axis: **tokens/s/chip** (the same accounting
as tools/bench_lm.py — utils/token_accounting.py) and **inter-token
latency p50/p99** from the server's own per-token histogram, measured
under overload when the open-loop rate exceeds capacity.  The smoke
artifact lives at ``artifacts/BENCH_decode_smoke.json``.

Prompt-heavy trace (``--decode --mode trace``): S streams whose
prompts share a ``--shared-prefix``-token system prefix and append
long-tail suffixes (``--tail-lengths``), each generating
``--gen-tokens`` — the workload the two token-throughput multipliers
exist for.  Reports **per-stream tok/s** (tokens / that stream's own
wall, queue included) and the server's accept-rate / prefix-cache
counters.  ``--spec-compare`` runs the SAME trace twice on fresh
in-process servers — baseline (no draft, prefix cache off) vs
optimized (speculative decoding + prefix cache) — verifies the two
legs' outputs are byte-identical, and emits one JSON with both legs
plus the per-stream speedup (committed:
``artifacts/BENCH_decode_spec.json``).  The demo draft is the target
re-exported at bf16 (self-speculation: same argmax almost always, so
it measures the accept machinery honestly; a real deployment exports
a separately trained smaller draft).

Emits one ``BENCH_serving`` JSON (throughput, latency p50/p95/p99,
batch occupancy / decode sharing from the server's own stats, overload
counts) to ``--out`` and prints it — same artifact discipline as the
other bench tools.

Usage:
    # against a running server (tmlocal SERVE ...):
    python tools/bench_serving.py --addr host:45900 --mode open --rate 200

    # self-contained (exports a tiny model, serves in-process, drives it):
    JAX_PLATFORMS=cpu python tools/bench_serving.py --demo --mode closed

    # token-throughput mode against a decode server (or --demo):
    JAX_PLATFORMS=cpu python tools/bench_serving.py --demo --decode \
        --mode open --rate 20 --gen-tokens 16
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _bootstrap  # noqa: F401,E402  (makes JAX_PLATFORMS effective)
import numpy as np  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _percentiles(ms: list[float]) -> dict:
    if not ms:
        return {}
    a = np.sort(np.asarray(ms))
    pick = lambda q: float(a[min(len(a) - 1, int(q * len(a)))])
    return {"mean": float(a.mean()), "p50": pick(0.50),
            "p95": pick(0.95), "p99": pick(0.99), "max": float(a[-1])}


def _demo_export(tmp_dir: str, decode: bool = False,
                 d_model: int = 32, n_layers: int = 2,
                 n_heads: int = 2, vocab: int = 64,
                 seq_len: int = 32, draft: str | None = None):
    """Export an untrained tiny model so the tool runs anywhere:
    TinyCifar for eval mode, a small TransformerLM for --decode
    (dims CLI-sized so the trace mode can make prefill compute-bound
    on the CPU box).  ``draft='bf16'`` additionally exports the same
    net quantized as the speculative draft (self-speculation) and
    returns (export_dir, draft_dir)."""
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.serving import export_model

    if decode:
        from theanompi_tpu.models.transformer import TransformerLM

        cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                          compute_dtype="float32", optimizer="adamw",
                          learning_rate=1e-3, weight_decay=0.0,
                          lr_schedule="constant")
        model = TransformerLM(config=cfg, vocab=vocab, seq_len=seq_len,
                              n_layers=n_layers, d_model=d_model,
                              n_heads=n_heads, verbose=False)
    else:
        from tests._tiny_models import TinyCifar

        model = TinyCifar(config=ModelConfig(batch_size=8, n_epochs=1,
                                             print_freq=0),
                          verbose=False)
    export_dir = os.path.join(tmp_dir, "export")
    export_model(model, export_dir, version=0)
    if not draft:
        return export_dir
    draft_dir = os.path.join(tmp_dir, "draft")
    export_model(model, draft_dir, version=0, weight_dtype="bf16")
    return export_dir, draft_dir


def _demo_trained_exports(tmp_dir: str, args):
    """Target + genuinely-smaller-draft demo exports for the trace
    mode's honest configuration: BOTH nets train
    ``--demo-train-epochs`` epochs on the synthetic successor-table
    LM task (data/lm.py, noise=0.15 so each learns a Markov rule
    robust to off-chain context) — after which the small draft agrees
    with the target on greedy rollouts because both learned the same
    table, which is exactly the regime speculative decoding is for.
    Returns (export_dir, draft_dir)."""
    from theanompi_tpu.data.lm import SeqLM_data
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.transformer import TransformerLM
    from theanompi_tpu.rules.bsp import run_bsp_session
    from theanompi_tpu.serving import export_model

    def build(d_model, n_layers, n_heads):
        cfg = ModelConfig(batch_size=16,
                          n_epochs=args.demo_train_epochs,
                          print_freq=0, compute_dtype="float32",
                          optimizer="adamw", learning_rate=3e-3,
                          weight_decay=0.0, lr_schedule="constant")
        data = SeqLM_data(vocab=args.demo_vocab,
                          seq_len=args.demo_seq_len, n_train=512,
                          n_val=64, seed=0, noise=0.15)
        return TransformerLM(config=cfg, vocab=args.demo_vocab,
                             seq_len=args.demo_seq_len,
                             n_layers=n_layers, d_model=d_model,
                             n_heads=n_heads, verbose=False, data=data)

    target = build(args.demo_d_model, args.demo_layers,
                   args.demo_heads)
    run_bsp_session(target, checkpoint=False)
    draft = build(args.demo_draft_d_model, args.demo_draft_layers,
                  args.demo_draft_heads)
    run_bsp_session(draft, checkpoint=False)
    export_dir = os.path.join(tmp_dir, "export")
    draft_dir = os.path.join(tmp_dir, "draft")
    export_model(target, export_dir, version=0)
    export_model(draft, draft_dir, version=0)
    return export_dir, draft_dir


def make_trace(shared_prefix: int, tail_lengths: list[int],
               streams: int, vocab: int, seed: int = 0) -> list:
    """The prompt-heavy trace: every stream's prompt = one shared
    system prefix + its own long-tail suffix (lengths cycled from
    ``tail_lengths``).  Deterministic, so compare legs replay
    byte-identical prompts."""
    rng = np.random.default_rng(seed)
    top = max(2, vocab - 1)
    prefix = (rng.integers(0, top, shared_prefix).astype(np.int32) + 1
              if shared_prefix else np.zeros((0,), np.int32))
    prompts = []
    for i in range(streams):
        tail = rng.integers(0, top,
                            tail_lengths[i % len(tail_lengths)])
        prompts.append(np.concatenate(
            [prefix, tail.astype(np.int32) + 1]))
    return prompts


def run_trace(addr: str, prompts: list, gen_tokens: int,
              concurrency: int) -> dict:
    """Drive one stream per prompt (own connection each — the server's
    admission bound, not a client pool, is what saturates), at most
    ``concurrency`` in flight.  Per-stream wall includes queueing —
    the number a user's stream actually experiences."""
    from theanompi_tpu.serving import InferenceClient, Overloaded

    sem = threading.Semaphore(concurrency)
    lock = threading.Lock()
    streams: list[dict | None] = [None] * len(prompts)
    counts = {"ok": 0, "overloaded": 0, "errors": 0}

    def one(i: int) -> None:
        with sem:
            t0 = time.monotonic()
            client = InferenceClient(addr)
            try:
                out = client.generate(prompts[i], gen_tokens)
            except Overloaded:
                with lock:
                    counts["overloaded"] += 1
                return
            except Exception:
                with lock:
                    counts["errors"] += 1
                return
            finally:
                client.close()
            wall = time.monotonic() - t0
            with lock:
                counts["ok"] += 1
                streams[i] = {"wall_s": wall, "tokens": len(out),
                              "prompt_tokens": int(prompts[i].shape[0]),
                              "out": [int(t) for t in out]}

    t_start = time.monotonic()
    threads = [threading.Thread(target=one, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    done = [s for s in streams if s is not None]
    per_stream = [s["tokens"] / s["wall_s"] for s in done
                  if s["wall_s"] > 0]
    return {
        "wall_s": wall,
        "streams": streams,
        "tokens": sum(s["tokens"] for s in done),
        "tok_s_per_stream": {
            "mean": float(np.mean(per_stream)) if per_stream else 0.0,
            "p50": float(np.median(per_stream)) if per_stream else 0.0,
            "min": float(np.min(per_stream)) if per_stream else 0.0,
            "max": float(np.max(per_stream)) if per_stream else 0.0,
        },
        **counts,
    }


def run_load(addr: str, sample: np.ndarray, mode: str, clients: int,
             rate: float, duration: float, decode: bool = False,
             gen_tokens: int = 16) -> dict:
    from theanompi_tpu.serving import InferenceClient, Overloaded

    lock = threading.Lock()
    lat_ms: list[float] = []
    counts = {"ok": 0, "overloaded": 0, "errors": 0, "tokens": 0}

    def one(client) -> None:
        t0 = time.monotonic()
        try:
            if decode:
                out = client.generate(sample, gen_tokens)
            else:
                client.infer(sample)
                out = None
        except Overloaded:
            with lock:
                counts["overloaded"] += 1
            return
        except Exception:
            with lock:
                counts["errors"] += 1
            return
        dt = (time.monotonic() - t0) * 1e3
        with lock:
            counts["ok"] += 1
            if out is not None:
                counts["tokens"] += len(out)
            lat_ms.append(dt)

    t_start = time.monotonic()
    if mode == "closed":
        def worker():
            client = InferenceClient(addr)
            while time.monotonic() - t_start < duration:
                one(client)
            client.close()

        threads = [threading.Thread(target=worker)
                   for _ in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    else:  # open loop: Poisson arrivals, one short-lived thread each
        rng = np.random.default_rng(0)
        # eval requests are ~ms, so a small shared client pool
        # approximates open-loop; a decode STREAM holds its connection
        # for the whole generation (ServiceClient serializes per
        # connection), so every in-flight stream needs its OWN
        # connection or the pool lock — not the server — caps
        # concurrency and the bench measures client queueing
        pool = ([] if decode
                else [InferenceClient(addr) for _ in range(clients)])

        def one_arrival(i: int) -> None:
            if decode:
                c = InferenceClient(addr)
                try:
                    one(c)
                finally:
                    c.close()
            else:
                one(pool[i % clients])

        inflight: list[threading.Thread] = []
        i = 0
        next_t = t_start
        while time.monotonic() - t_start < duration:
            next_t += float(rng.exponential(1.0 / rate))
            delay = next_t - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=one_arrival, args=(i,))
            t.start()
            inflight.append(t)
            i += 1
        for t in inflight:
            t.join()
        for c in pool:
            c.close()
    wall = time.monotonic() - t_start
    return {"wall_s": wall, "latency_ms": _percentiles(lat_ms),
            **counts,
            "throughput_rps": counts["ok"] / wall if wall else 0.0}


def _start_decode_server(export_dir: str, args, draft_dir: str | None,
                         prefix_cache: bool):
    from theanompi_tpu.serving import InferenceServer, serve

    decode_opts = dict(
        max_seqs=args.decode_max_seqs,
        max_pending=args.decode_max_pending,
        page_size=args.decode_page_size,
        pages_per_seq=args.decode_pages_per_seq,
        prefix_cache=prefix_cache)
    if args.decode_prefill_buckets:
        decode_opts["prefill_buckets"] = tuple(
            int(b) for b in args.decode_prefill_buckets.split(","))
    if draft_dir:
        decode_opts["draft_export_dir"] = draft_dir
        decode_opts["speculate_k"] = args.speculate_k
    server = InferenceServer(export_dir, replicas=args.replicas,
                             decode=True, decode_opts=decode_opts,
                             reload_poll_s=0).start()
    port = _free_port()
    ready = threading.Event()
    thread = threading.Thread(
        target=serve, args=(server, "127.0.0.1", port, ready),
        daemon=True)
    thread.start()
    assert ready.wait(60), "server never came up"
    return server, thread, f"127.0.0.1:{port}"


def trace_main(args, tmp_dir: str) -> dict:
    """The prompt-heavy trace: one leg honoring the flags, or — with
    ``--spec-compare`` — baseline vs optimized legs on fresh
    in-process servers, byte-identity-checked (module docstring)."""
    from theanompi_tpu.serving import InferenceClient, load_export
    from theanompi_tpu.utils.token_accounting import token_throughput

    export_dir = args.export_dir
    draft_dir = args.draft_export_dir
    if export_dir is None:
        if not args.demo:
            raise SystemExit(
                "--mode trace needs --export-dir or --demo (it "
                "starts its own in-process servers)")
        if args.demo_train_epochs > 0:
            export_dir, draft_dir = _demo_trained_exports(tmp_dir,
                                                          args)
        else:
            export_dir, draft_dir = _demo_export(
                tmp_dir, decode=True, d_model=args.demo_d_model,
                n_layers=args.demo_layers, n_heads=args.demo_heads,
                vocab=args.demo_vocab, seq_len=args.demo_seq_len,
                draft="bf16")
    meta = load_export(export_dir).meta
    vocab = int((meta.get("net") or {}).get("vocab", 64))
    tails = [int(x) for x in args.tail_lengths.split(",")]
    prompts = make_trace(args.shared_prefix, tails, args.streams,
                         vocab)
    if args.spec_compare:
        if draft_dir is None:
            raise SystemExit(
                "--spec-compare needs a draft: pass "
                "--draft-export-dir with --export-dir, or use --demo "
                "(which exports one) — otherwise the 'optimized' leg "
                "would silently run without speculation")
        plan = (("baseline", False, False), ("optimized", True, True))
    else:
        plan = (("trace", bool(draft_dir),
                 not args.no_prefix_cache),)
    legs = {}
    for name, use_draft, use_prefix in plan:
        server, thread, addr = _start_decode_server(
            export_dir, args, draft_dir if use_draft else None,
            use_prefix)
        try:
            probe = InferenceClient(addr)
            # warm pass: compiles every (bucket, family) the trace
            # touches and seeds the prefix cache — the measured pass
            # is the steady state users live in
            run_trace(addr, prompts, args.gen_tokens,
                      args.concurrency)
            warm_compiles = [
                {"target": r.get("compiles"),
                 "draft": r.get("draft_compiles")}
                for r in probe.stats()["replicas"]]
            res = run_trace(addr, prompts, args.gen_tokens,
                            args.concurrency)
            st = probe.stats()
            probe.shutdown()
            probe.close()
        finally:
            server.stop()
            thread.join(timeout=10)
        measured_compiles = [
            {"target": r.get("compiles"),
             "draft": r.get("draft_compiles")}
            for r in st["replicas"]]
        legs[name] = {
            "speculative": use_draft,
            "prefix_cache": use_prefix,
            "tok_s_per_stream": res["tok_s_per_stream"],
            "throughput": token_throughput(res["tokens"],
                                           res["wall_s"]),
            "wall_s": res["wall_s"],
            "ok": res["ok"], "overloaded": res["overloaded"],
            "errors": res["errors"],
            "outputs": [s["out"] if s else None
                        for s in res["streams"]],
            "server": {
                "tokens": st.get("tokens"),
                "steps": st.get("steps"),
                "mean_tokens_per_step": (st["tokens"] / st["steps"]
                                         if st.get("steps") else None),
                "accept_rate": st.get("accept_rate"),
                "prefix_cache_hits": st.get("prefix_cache_hits"),
                "intertoken_ms": (st["replicas"][0] or {}).get(
                    "intertoken_ms"),
            },
            # steady-state pin: the measured pass may not compile
            # anything the warm pass did not
            "zero_steady_state_recompiles":
                warm_compiles == measured_compiles,
            "compiles": measured_compiles,
        }
    out = {
        "bench": "serving",
        "mode": "trace",
        "decode": True,
        "argv": sys.argv[1:],
        "trace": {
            "streams": args.streams,
            "shared_prefix_tokens": args.shared_prefix,
            "tail_lengths": tails,
            "gen_tokens_per_stream": args.gen_tokens,
            "concurrency": args.concurrency,
            "speculate_k": args.speculate_k,
        },
        "model": {"net": meta.get("net"),
                  "weight_dtype": meta.get("weight_dtype")},
        "legs": {name: {k: v for k, v in leg.items()
                        if k != "outputs"}
                 for name, leg in legs.items()},
    }
    if args.spec_compare:
        base, opt = legs["baseline"], legs["optimized"]
        out["byte_identical_output"] = (base["outputs"]
                                        == opt["outputs"])
        b = base["tok_s_per_stream"]["mean"]
        o = opt["tok_s_per_stream"]["mean"]
        out["per_stream_speedup"] = o / b if b else None
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--addr", default=None,
                    help="host:port of a running server; omitted = "
                         "serve --export-dir in-process")
    ap.add_argument("--export-dir", default=None)
    ap.add_argument("--demo", action="store_true",
                    help="export an untrained TinyCifar to a temp dir "
                         "first (self-contained CPU run)")
    ap.add_argument("--mode", choices=("closed", "open", "trace"),
                    default="closed",
                    help="closed/open loop, or 'trace' — the decode "
                         "prompt-heavy trace (shared prefix x many "
                         "streams, per-stream tok/s)")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--rate", type=float, default=100.0,
                    help="open-loop arrival rate, req/s")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per request")
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=32)
    ap.add_argument("--decode", action="store_true",
                    help="token-throughput mode: drive 'generate' "
                         "streams against a decode server (tokens/s/"
                         "chip + inter-token p50/p99 headline)")
    ap.add_argument("--prompt-tokens", type=int, default=8,
                    help="--decode: prompt length per stream")
    ap.add_argument("--gen-tokens", type=int, default=16,
                    help="--decode: tokens generated per stream")
    ap.add_argument("--decode-max-seqs", type=int, default=8,
                    help="--decode in-process server: max concurrent "
                         "sequences per replica")
    ap.add_argument("--decode-max-pending", type=int, default=32,
                    help="--decode in-process server: admission bound "
                         "(prompts beyond it get Overloaded)")
    ap.add_argument("--decode-page-size", type=int, default=16,
                    help="--decode in-process trace server: tokens "
                         "per KV page")
    ap.add_argument("--decode-pages-per-seq", type=int, default=8,
                    help="--decode in-process trace server: pages per "
                         "sequence (window = page_size x pages)")
    ap.add_argument("--decode-prefill-buckets", default=None,
                    metavar="N,N,...",
                    help="--decode in-process trace server: padded "
                         "prompt-length buckets")
    ap.add_argument("--shared-prefix", type=int, default=0,
                    help="--mode trace: shared system-prefix tokens "
                         "prepended to every stream's prompt")
    ap.add_argument("--tail-lengths", default="1,2,4,8,16",
                    help="--mode trace: long-tail per-stream prompt "
                         "suffix lengths, cycled")
    ap.add_argument("--streams", type=int, default=16,
                    help="--mode trace: generation streams in the "
                         "trace")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="--mode trace: max streams in flight")
    ap.add_argument("--speculate-k", type=int, default=4,
                    help="draft tokens per speculative round")
    ap.add_argument("--draft-export-dir", default=None,
                    help="speculative draft export for the in-process "
                         "server (--demo exports a bf16 self-draft)")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="--mode trace single leg: disable the prefix "
                         "cache")
    ap.add_argument("--spec-compare", action="store_true",
                    help="--mode trace: run baseline (no draft, no "
                         "prefix cache) and optimized (both on) legs "
                         "over the SAME trace, verify byte-identical "
                         "outputs, report the per-stream speedup")
    ap.add_argument("--demo-d-model", type=int, default=32)
    ap.add_argument("--demo-layers", type=int, default=2)
    ap.add_argument("--demo-heads", type=int, default=2)
    ap.add_argument("--demo-vocab", type=int, default=64)
    ap.add_argument("--demo-seq-len", type=int, default=32)
    ap.add_argument("--demo-train-epochs", type=int, default=0,
                    help="--mode trace --demo: train target AND a "
                         "smaller draft net this many epochs on the "
                         "synthetic successor-table LM task before "
                         "exporting (0 = untrained target with a bf16 "
                         "self-draft)")
    ap.add_argument("--demo-draft-d-model", type=int, default=64)
    ap.add_argument("--demo-draft-layers", type=int, default=1)
    ap.add_argument("--demo-draft-heads", type=int, default=2)
    ap.add_argument("--out", default="BENCH_serving.json")
    args = ap.parse_args(argv)

    if args.mode == "trace":
        if not args.decode:
            ap.error("--mode trace is a --decode mode")
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            out = trace_main(args, td)
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps(out, indent=1))
        print(f"BENCH_serving written to {args.out}")
        return 0

    import tempfile

    from theanompi_tpu.serving import (
        BatchPolicy,
        InferenceClient,
        InferenceServer,
        load_export,
        serve,
    )

    tmp_ctx = tempfile.TemporaryDirectory()
    server = thread = None
    try:
        if args.addr is None:
            export_dir = args.export_dir
            if export_dir is None:
                if not args.demo:
                    ap.error("need --addr, --export-dir, or --demo")
                export_dir = _demo_export(tmp_ctx.name,
                                          decode=args.decode)
            policy = BatchPolicy(max_batch=args.max_batch,
                                 max_delay_ms=args.max_delay_ms,
                                 max_queue=args.max_queue)
            decode_opts = (dict(max_seqs=args.decode_max_seqs,
                                max_pending=args.decode_max_pending)
                           if args.decode else None)
            server = InferenceServer(export_dir,
                                     replicas=args.replicas,
                                     policy=policy,
                                     decode=args.decode,
                                     decode_opts=decode_opts).start()
            port = _free_port()
            ready = threading.Event()
            thread = threading.Thread(
                target=serve, args=(server, "127.0.0.1", port, ready),
                daemon=True)
            thread.start()
            assert ready.wait(30), "server never came up"
            addr = f"127.0.0.1:{port}"
            meta = load_export(export_dir).meta
        else:
            addr = args.addr
            if args.export_dir:
                meta = load_export(args.export_dir).meta
            else:
                meta = {}
        if args.decode:
            vocab = int((meta.get("net") or {}).get("vocab", 64))
            sample = (np.arange(args.prompt_tokens, dtype=np.int32)
                      % max(2, vocab - 1)) + 1
        else:
            shape = tuple(meta.get("sample_shape") or (32, 32, 3))
            dtype = np.dtype(meta.get("sample_dtype") or "uint8")
            sample = np.zeros((args.rows, *shape), dtype)

        probe = InferenceClient(addr)
        if args.decode:  # one warm stream outside the window
            probe.generate(sample, args.gen_tokens)
        else:
            probe.infer(sample)
        result = run_load(addr, sample, args.mode, args.clients,
                          args.rate, args.duration,
                          decode=args.decode,
                          gen_tokens=args.gen_tokens)
        stats = probe.stats()
        if server is not None:
            probe.shutdown()
        probe.close()
        out = {
            "bench": "serving",
            "mode": args.mode,
            "decode": args.decode,
            "clients": args.clients,
            "rate_rps": args.rate if args.mode == "open" else None,
            "server": {
                "addr": addr,
                "version": stats.get("version"),
                "replicas": stats.get("live_replicas"),
                "overloaded": stats.get("overloaded"),
            },
            **result,
        }
        if args.decode:
            # tokens/s accounted identically to training bench_lm.py
            from theanompi_tpu.utils.token_accounting import (
                token_throughput,
            )

            n_chips = 1
            if server is not None:
                import jax

                n_chips = len(jax.devices())
            reps = stats.get("replicas") or [{}]
            out.update(
                prompt_tokens=args.prompt_tokens,
                gen_tokens_per_stream=args.gen_tokens,
                throughput=token_throughput(result["tokens"],
                                            result["wall_s"], n_chips),
                intertoken_ms=reps[0].get("intertoken_ms"),
                server_decode={
                    "tokens": stats.get("tokens"),
                    "steps": stats.get("steps"),
                    "shared_steps": stats.get("shared_steps"),
                    "max_concurrent": stats.get("max_concurrent"),
                    "mean_tokens_per_step": (
                        stats["tokens"] / stats["steps"]
                        if stats.get("steps") else None),
                },
            )
        else:
            out.update(
                rows_per_request=args.rows,
                server_batching={
                    "batches": stats.get("batches"),
                    "batch_rows": stats.get("rows"),
                    "max_occupancy": stats.get("max_occupancy"),
                    "mean_occupancy": (stats["rows"] / stats["batches"]
                                       if stats.get("batches")
                                       else None),
                },
            )
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps(out, indent=1))
        print(f"BENCH_serving written to {args.out}")
        return 0
    finally:
        if server is not None:
            server.stop()
        tmp_ctx.cleanup()


if __name__ == "__main__":
    raise SystemExit(main())
