"""Transformer-LM training throughput — tokens/sec/chip + TFLOP/s.

The CNN flagship has bench.py; this gives the transformer family the
same on-chip measurement surface (the LM family declares its trained
FLOPs from the real param count, models/transformer.py), so a chip
window can quantify the fused-attention + remat stack, not just
ResNet.  One JSON line, bench.py conventions (pre-staged batches,
value-readback fencing).

    python tools/bench_lm.py --batch 8 --seq 1024 --layers 12 \
        --d-model 768 --steps 20
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import _bootstrap  # noqa: F401,E402  (makes JAX_PLATFORMS effective)
import jax  # noqa: E402
import numpy as np  # noqa: E402

from bench import fenced_loss  # noqa: E402  (shared axon-safe fence)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8,
                    help="sequences per data shard")
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--attn", default=None,
                    choices=("auto", "pallas", "xla"),
                    help="force the attention impl (the r3 'fused' "
                    "points exported THEANOMPI_TPU_ATTN_IMPL by hand; "
                    "a flag makes the queue JSON self-contained)")
    args = ap.parse_args()
    if args.attn:
        os.environ["THEANOMPI_TPU_ATTN_IMPL"] = args.attn

    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.transformer import TransformerLM
    from theanompi_tpu.parallel.mesh import data_mesh, shard_batch

    devices = jax.devices()
    mesh = data_mesh(len(devices), devices)
    cfg = ModelConfig(batch_size=args.batch, n_epochs=1,
                      optimizer="adamw", learning_rate=1e-3,
                      weight_decay=0.01, lr_schedule="constant",
                      compute_dtype=args.dtype, remat=args.remat,
                      print_freq=10**9)
    model = TransformerLM(config=cfg, mesh=mesh, vocab=args.vocab,
                          seq_len=args.seq, n_layers=args.layers,
                          d_model=args.d_model, n_heads=args.heads,
                          verbose=False)
    model.compile_iter_fns("avg")
    global_batch = model.global_batch
    # stage with the MODEL's partition (P('data','seq') for the LM) so
    # jit never reshards inside the timed loop
    staged = [shard_batch(b, mesh, spec=model.batch_partition)
              for _, b in zip(
                  range(2), model.data.train_batches(0, global_batch))]

    rng = jax.random.key(0)
    state = model.state
    for i in range(2):  # compile + settle
        state, metrics = model.train_step(state, staged[i % 2], rng)
    fenced_loss(metrics)
    t0 = time.perf_counter()
    for i in range(args.steps):
        state, metrics = model.train_step(state, staged[i % 2], rng)
    loss = fenced_loss(metrics)
    dt = time.perf_counter() - t0
    assert np.isfinite(loss), loss
    model.cleanup()

    # one shared definition with bench_serving's decode mode
    # (utils/token_accounting.py): training tokens are every position
    # of every sequence, over the timed window, per chip
    from theanompi_tpu.utils.token_accounting import token_throughput

    rate = token_throughput(args.steps * global_batch * args.seq, dt,
                            len(devices))
    tflops = (args.steps * global_batch * model.train_flops_per_sample
              / dt / 1e12)
    print(json.dumps({
        "metric": "transformer_lm_tokens_per_sec_per_chip",
        "value": round(rate["tokens_per_sec_per_chip"], 1),
        "unit": "tokens/sec/chip",
        "detail": {
            "n_chips": len(devices),
            "tokens": rate["tokens"],
            "global_batch": global_batch,
            "seq_len": args.seq,
            "layers": args.layers, "d_model": args.d_model,
            "remat": args.remat, "dtype": args.dtype,
            "attn": args.attn or os.environ.get(
                "THEANOMPI_TPU_ATTN_IMPL", "auto"),
            "step_ms": round(dt / args.steps * 1e3, 2),
            "tflops_per_chip": round(tflops / len(devices), 2),
            "train_gflops_per_seq": round(
                model.train_flops_per_sample / 1e9, 2),
            "final_loss": round(loss, 4),
            "backend": jax.default_backend(),
        },
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
