#!/usr/bin/env python
"""Assemble distributed traces from a merged fleet JSONL.

Consumes the collector's ``fleet.jsonl`` (or a run dir of per-process
``events_*.jsonl`` files when no collector ran) and prints, per trace:
the span tree, the process fan-out, orphan count, and the **critical
path** — the chain of spans that bounds the trace's wall time, which
is where an exchange period or a GENERATE request actually spent its
time.  Also runs **idle-all-workers gap detection** (ROADMAP item 2's
acceptance metric): intervals inside the observation window where NO
process had any span open — the keep-the-device-busy discipline of
the source paper, made checkable.

Wall timestamps are mapped onto the collector's clock before any
cross-process comparison: each record carries the sender's estimated
``offset_s`` (sampled from the export handshake round trip — see
docs/OBSERVABILITY.md "Distributed tracing").

Usage:
    python tools/traces.py RUNDIR_OR_FLEET_JSONL [--gap-ms 50]
        [--trace ID] [--min-spans 2] [--require-procs N]
        [--require-zero-orphans]

Exit status: 0, or 1 when a ``--require-*`` assertion fails (the
preflight collector smoke drives these).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------


def _read_jsonl(path: str) -> list[dict]:
    out: list[dict] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail line mid-write
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def _with_rotations(path: str) -> list[str]:
    rotated, i = [], 1
    while os.path.exists(f"{path}.{i}"):
        rotated.append(f"{path}.{i}")
        i += 1
    return [*reversed(rotated), path]


def load_events(target: str) -> list[dict]:
    """Records from a fleet JSONL, or from every event file under a
    run dir (fleet.jsonl preferred; falls back to the per-process
    local files so traces assemble even with no collector)."""
    if os.path.isdir(target):
        fleet = os.path.join(target, "fleet.jsonl")
        paths: list[str] = []
        if os.path.exists(fleet):
            paths = _with_rotations(fleet)
        else:
            for p in sorted(glob.glob(
                    os.path.join(target, "events_*.jsonl"))):
                if not p.rsplit(".", 1)[-1].isdigit():
                    paths.extend(_with_rotations(p))
        out: list[dict] = []
        for p in paths:
            out.extend(_read_jsonl(p))
        return out
    out = []
    for p in _with_rotations(target):
        out.extend(_read_jsonl(p))
    return out


# ---------------------------------------------------------------------------
# Trace assembly
# ---------------------------------------------------------------------------


def spans_of(records: list[dict]) -> list[dict]:
    """Span records with collector-clock times attached: ``t0`` /
    ``t1`` are offset-corrected wall seconds."""
    out = []
    for r in records:
        if r.get("event") != "span" or not r.get("trace"):
            continue
        try:
            off = float(r.get("offset_s") or 0.0)
            dur = float(r.get("dur_s") or 0.0)
            t0 = float(r["t_wall"]) + off
        except (KeyError, TypeError, ValueError):
            continue
        s = dict(r)
        s["t0"], s["t1"] = t0, t0 + dur
        out.append(s)
    return out


def assemble(records: list[dict]) -> dict[str, list[dict]]:
    """trace_id -> spans, each trace sorted by corrected start."""
    traces: dict[str, list[dict]] = {}
    for s in spans_of(records):
        traces.setdefault(s["trace"], []).append(s)
    for spans in traces.values():
        spans.sort(key=lambda s: s["t0"])
    return traces


def orphans(spans: list[dict]) -> list[dict]:
    """Spans whose declared parent is missing from the trace — a
    broken stitch (dropped span, or a propagation hole)."""
    ids = {s["span"] for s in spans}
    return [s for s in spans
            if s.get("parent") is not None and s["parent"] not in ids]


def processes_of(spans: list[dict]) -> set:
    return {(s.get("pid"), s.get("role")) for s in spans}


def critical_path(spans: list[dict]) -> list[dict]:
    """Root-to-leaf chain that bounds the trace's wall time: from each
    node, descend into the child whose (corrected) end time is
    latest.  Roots are parentless spans (plus orphans, so a damaged
    trace still yields a path); among roots the latest-ending wins."""
    if not spans:
        return []
    ids = {s["span"] for s in spans}
    children: dict[str, list[dict]] = {}
    roots: list[dict] = []
    for s in spans:
        p = s.get("parent")
        if p is not None and p in ids:
            children.setdefault(p, []).append(s)
        else:
            roots.append(s)
    path: list[dict] = []
    node = max(roots, key=lambda s: s["t1"])
    seen = set()
    while node is not None and node["span"] not in seen:
        seen.add(node["span"])
        path.append(node)
        kids = children.get(node["span"], [])
        node = max(kids, key=lambda s: s["t1"]) if kids else None
    return path


# ---------------------------------------------------------------------------
# Idle-all-workers gaps
# ---------------------------------------------------------------------------


def idle_gaps(spans: list[dict], threshold_s: float = 0.05
              ) -> list[tuple[float, float]]:
    """Intervals of the observation window (first span start to last
    span end, collector clock) longer than ``threshold_s`` during
    which NO span was open in ANY process.  Zero gaps is the
    keep-the-device-busy acceptance condition; each gap is dead fleet
    time nothing was attributed to."""
    ivals = sorted((s["t0"], s["t1"]) for s in spans)
    if not ivals:
        return []
    gaps: list[tuple[float, float]] = []
    cover_end = ivals[0][1]
    for t0, t1 in ivals[1:]:
        if t0 > cover_end and t0 - cover_end >= threshold_s:
            gaps.append((cover_end, t0))
        cover_end = max(cover_end, t1)
    return gaps


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _fmt_span(s: dict) -> str:
    labels = s.get("labels") or {}
    lab = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    who = f"{s.get('role')}/pid{s.get('pid')}"
    return (f"{s.get('name')}{'{' + lab + '}' if lab else ''} "
            f"[{who}] {float(s.get('dur_s') or 0.0) * 1e3:.2f}ms")


def print_trace(tid: str, spans: list[dict], file=None) -> None:
    file = file if file is not None else sys.stdout
    orph = orphans(spans)
    procs = processes_of(spans)
    t0 = min(s["t0"] for s in spans)
    t1 = max(s["t1"] for s in spans)
    print(f"trace {tid}: {len(spans)} spans, {len(procs)} processes, "
          f"{(t1 - t0) * 1e3:.2f}ms wall, {len(orph)} orphans",
          file=file)
    path = critical_path(spans)
    path_ids = {s["span"] for s in path}
    print("  critical path:", file=file)
    for depth, s in enumerate(path):
        print(f"    {'  ' * depth}{_fmt_span(s)}", file=file)
    rest = [s for s in spans if s["span"] not in path_ids]
    if rest:
        print(f"  off-path spans ({len(rest)}):", file=file)
        for s in rest:
            print(f"    {_fmt_span(s)}", file=file)
    for s in orph:
        print(f"  ORPHAN {_fmt_span(s)} "
              f"(parent {s.get('parent')} missing)", file=file)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="assemble distributed traces from a fleet JSONL "
                    "(docs/OBSERVABILITY.md 'Distributed tracing')")
    ap.add_argument("target",
                    help="fleet.jsonl (or a run dir containing it / "
                         "per-process events_*.jsonl files)")
    ap.add_argument("--trace", default=None,
                    help="print only this trace id")
    ap.add_argument("--min-spans", type=int, default=2,
                    help="hide traces smaller than this (default 2; "
                         "single-span traces are usually untraced "
                         "background noise)")
    ap.add_argument("--gap-ms", type=float, default=50.0,
                    help="idle-all-workers gap threshold (default 50)")
    ap.add_argument("--require-procs", type=int, default=0,
                    help="exit 1 unless some trace spans >= N "
                         "processes with zero orphans (preflight)")
    ap.add_argument("--require-zero-orphans", action="store_true",
                    help="exit 1 if any printed trace has orphans")
    args = ap.parse_args(argv)

    records = load_events(args.target)
    traces = assemble(records)
    if args.trace:
        traces = {k: v for k, v in traces.items() if k == args.trace}
    shown = {tid: spans for tid, spans in traces.items()
             if len(spans) >= args.min_spans}
    all_spans = [s for spans in traces.values() for s in spans]
    if not shown:
        print(f"no traces with >= {args.min_spans} spans "
              f"({len(all_spans)} span records total)")
    for tid, spans in sorted(shown.items(),
                             key=lambda kv: kv[1][0]["t0"]):
        print_trace(tid, spans)

    gaps = idle_gaps(all_spans, args.gap_ms / 1e3)
    if gaps:
        print(f"idle-all-workers gaps (> {args.gap_ms:.0f}ms): "
              f"{len(gaps)}")
        for g0, g1 in gaps:
            print(f"  {(g1 - g0) * 1e3:.1f}ms dead at +"
                  f"{(g0 - all_spans[0]['t0']):.3f}s")
    else:
        print(f"idle-all-workers gaps (> {args.gap_ms:.0f}ms): none")

    rc = 0
    if args.require_zero_orphans and any(
            orphans(spans) for spans in shown.values()):
        print("FAIL: orphan spans present", file=sys.stderr)
        rc = 1
    if args.require_procs:
        ok = any(len(processes_of(spans)) >= args.require_procs
                 and not orphans(spans) for spans in shown.values())
        if not ok:
            print(f"FAIL: no complete trace spanning >= "
                  f"{args.require_procs} processes", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `traces.py ... | head` is a normal use
        sys.exit(0)
