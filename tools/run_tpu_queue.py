"""Sequential on-chip experiment queue (VERDICT r2 next-round #1).

The axon TPU tunnel serves in rare windows (BASELINE.md "Round-2
on-chip caveat"), so every on-chip experiment runs from this one
queue: each experiment is a SUBPROCESS with its own timeout, and every
result — success or failure — is appended to the queue JSONL the
moment it lands, so a mid-run wedge loses only the in-flight point.
``tools/harvest_queue.py`` turns the log into the decision table and
tuned bench defaults.

Priority order front-loads the decisions the round needs: the k-ladder
(does multi-step scan amortize dispatch on real silicon?), then batch,
then stem, then the per-op MFU ladder, attention microbench, and the
3-epoch CIFAR smoke train with snapshots in artifacts/tpu_smoke.

Usage:
    python tools/run_tpu_queue.py --out /tmp/tpu_queue.jsonl
    python tools/run_tpu_queue.py --only resnet  # just the ladder
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time


def run_sub(argv, timeout, env, cwd=None):
    """subprocess.run replacement that survives axon-client children.

    The axon jax client spawns helper grandchildren that inherit
    stdout/stderr; with ``subprocess.run(capture_output=True,
    timeout=...)`` the post-kill ``communicate()`` then blocks forever
    on the pipe the orphans still hold (observed live: a 150 s probe
    still "running" at 9 min).  File-backed stdio can't hang, and
    ``killpg`` on the child's fresh session nukes the grandchildren
    too.  Returns (rc, stdout, stderr, timed_out); rc is None iff
    timed out."""
    with tempfile.TemporaryFile() as fo, tempfile.TemporaryFile() as fe:
        p = subprocess.Popen(argv, stdout=fo, stderr=fe, env=env,
                             cwd=cwd, start_new_session=True)
        try:
            rc, timed_out = p.wait(timeout=timeout), False
        except subprocess.TimeoutExpired:
            rc, timed_out = None, True
            try:
                os.killpg(p.pid, signal.SIGKILL)  # pgid==pid: new session
            except ProcessLookupError:
                pass
            p.wait()
        fo.seek(0)
        fe.seek(0)
        out = fo.read().decode(errors="replace")
        err = fe.read().decode(errors="replace")
    return rc, out, err, timed_out

TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS)
PY = sys.executable
MAX_ATTEMPTS = int(os.environ.get("THEANOMPI_TPU_QUEUE_ATTEMPTS", "3"))


PROBE_CODE = "import jax; print(jax.devices()[0].platform)"


def wait_for_tunnel(emit, env, poll_timeout: int, poll_interval: int):
    """Block until a fresh client can initialize the backend.

    A client that STARTS during a wedge fails UNAVAILABLE ~25 min
    later even if the tunnel recovers meanwhile (round-2/3 pattern:
    wall_s 1503 on every wedged attempt), so long experiment timeouts
    can sleep through an entire serving window.  Round 2's supervisor
    retried every ~2 min for 7+ hours and still caught the one window
    that opened — short-cadence probing neither prevents recovery nor
    misses windows.  Healthy tunnels answer the probe in ~15-40 s.
    """
    t0 = time.time()
    attempts = 0
    while True:
        attempts += 1
        rc, out, _err, _to = run_sub([PY, "-c", PROBE_CODE], poll_timeout,
                                     env)
        if rc == 0 and out.strip():
            if attempts > 1:
                emit({"event": "tunnel_up",
                      "waited_s": round(time.time() - t0, 1),
                      "probe_attempts": attempts})
            return
        if attempts == 1:
            emit({"event": "tunnel_wait", "ts": time.time()})
        time.sleep(poll_interval)


def experiments(smoke_dir: str):
    """(name, argv, timeout_s) in priority order.

    Timeouts are sized for a HEALTHY tunnel plus margin — the gate
    probe in the main loop ensures experiments only launch when a
    fresh client just initialized, so a block longer than the timeout
    means the window closed mid-experiment: reclaim and requeue.
    Healthy runtimes are 2-4 min per ResNet point."""
    pt = os.path.join(TOOLS, "queue_resnet_point.py")
    exps = []
    # 1. k-ladder at the round-2 default batch: the dispatch-floor
    # question.  k=1 first revalidates the baseline in this window.
    for k in (1, 4, 8):
        exps.append((f"resnet_k{k}_b128_conv7",
                     [PY, pt, "--k", str(k), "--batch", "128"], 900))
    # 2. batch ladder at each k (compile per point; b=256 halves the
    # dispatch count per image even at k=1)
    for k in (1, 4, 8):
        exps.append((f"resnet_k{k}_b256_conv7",
                     [PY, pt, "--k", str(k), "--batch", "256"], 900))
    # 3. the s2d stem (MXU-friendly 4x4 stem) at the two extremes
    exps.append(("resnet_k1_b128_s2d",
                 [PY, pt, "--k", "1", "--batch", "128", "--stem", "s2d"],
                 900))
    exps.append(("resnet_k8_b256_s2d",
                 [PY, pt, "--k", "8", "--batch", "256", "--stem", "s2d"],
                 900))
    # 4. per-op MFU account (VERDICT r2 #2): every distinct conv shape
    # timed fwd and fwd+bwd, reconciled against the full step
    exps.append(("conv_ladder_b128",
                 [PY, os.path.join(TOOLS, "conv_ladder.py"),
                  "--batch", "128"], 3600))
    # 5. attention microbench: validates the Pallas 'auto' default on
    # real silicon (ADVICE r2: ragged fwd only ever ran in interpret)
    exps.append(("attention_b8_t1024",
                 [PY, os.path.join(TOOLS, "bench_attention.py"),
                  "8", "1024"], 1200))
    # 6. 3-epoch CIFAR smoke through the full rule/recorder/checkpoint
    # spine, snapshots into the repo as the round's on-chip artifact
    exps.append(("cifar10_smoke",
                 [PY, "-m", "theanompi_tpu.launcher", "BSP",
                  "-m", "cifar10", "--epochs", "3",
                  "--snapshot-dir", smoke_dir,
                  "--result-json", os.path.join(smoke_dir, "result.json")],
                 3600))
    return exps


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/tmp/tpu_queue.jsonl")
    ap.add_argument("--only", default=None,
                    help="substring filter on experiment names")
    ap.add_argument("--smoke-dir",
                    default=os.path.join(REPO, "artifacts", "tpu_smoke"))
    ap.add_argument("--exps-json", default=None,
                    help="JSON file with [[name, argv, timeout_s], ...] "
                    "overriding the built-in ladder — lets tests drive "
                    "the timeout/requeue/forwarding machinery with stub "
                    "commands, and operators replay a subset")
    ap.add_argument("--compilation-cache-dir",
                    default=os.path.join(REPO, "artifacts", "jax_cache"),
                    help="persistent XLA compilation cache shared by "
                    "every queued experiment (exported as "
                    "THEANOMPI_TPU_COMPILATION_CACHE): a repeat window "
                    "skips the measured 39.3 s ResNet-50 compile "
                    "instead of burning a third of a 10-minute tunnel "
                    "window on it; pass '' to disable")
    ap.add_argument("--poll-timeout", type=int, default=150,
                    help="gate-probe client timeout (healthy tunnels "
                    "answer in ~15-40s; a wedged one just blocks)")
    ap.add_argument("--poll-interval", type=int, default=90,
                    help="sleep between gate probes while wedged")
    ap.add_argument("--gate", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="probe the tunnel before each experiment; "
                    "default: on for the built-in on-chip ladder, off "
                    "for --exps-json (stub tests) — pass --gate with "
                    "--exps-json for injected ON-CHIP experiment lists")
    args = ap.parse_args()
    gate = args.gate if args.gate is not None else not args.exps_json

    sink = open(args.out, "a", buffering=1)

    def emit(obj):
        line = json.dumps(obj)
        sink.write(line + "\n")
        print(line, flush=True)

    env = dict(os.environ)
    # Keep JAX_PLATFORMS / PYTHONPATH exactly as the image sets them
    # (JAX_PLATFORMS=axon + PYTHONPATH=/root/.axon_site): clearing the
    # platform pin sends the plugin through autodiscovery, which wedges
    # device init on this tunnel — but refuse a CPU override outright,
    # since the built-in queue exists to measure the chip.  Injected
    # --exps-json experiments carry their own platform choices (that is
    # how tests drive this machinery off-chip).
    if (not args.exps_json
            and env.get("JAX_PLATFORMS") not in (None, "", "axon", "tpu")):
        raise SystemExit(f"JAX_PLATFORMS={env['JAX_PLATFORMS']!r} would "
                         "run the on-chip queue off-chip; unset it")
    env.setdefault("THEANOMPI_TPU_SERVICE_KEY", "queue-local")
    if args.compilation_cache_dir:
        # children (bench.py, tmlocal runs) read the env var and call
        # enable_compilation_cache themselves — one cache per queue
        os.makedirs(args.compilation_cache_dir, exist_ok=True)
        env.setdefault("THEANOMPI_TPU_COMPILATION_CACHE",
                       args.compilation_cache_dir)

    if args.exps_json:
        with open(args.exps_json) as fh:
            exps = [tuple(e) for e in json.load(fh)]
    else:
        exps = experiments(args.smoke_dir)
    todo = [(name, argv, timeout, 1) for name, argv, timeout in exps
            if not args.only or args.only in name]
    emit({"event": "queue_start", "n_experiments": len(todo),
          "ts": time.time()})
    os.makedirs(args.smoke_dir, exist_ok=True)

    while todo:
        name, argv, timeout, attempt = todo.pop(0)
        if gate:
            wait_for_tunnel(emit, env, args.poll_timeout,
                            args.poll_interval)
        t0 = time.time()
        emit({"event": "start", "name": name, "attempt": attempt})
        rc, out, errout, timed_out = run_sub(argv, timeout, env, cwd=REPO)
        wall = round(time.time() - t0, 1)
        if timed_out or rc != 0:
            err = (f"timeout after {timeout}s (window closed "
                   "mid-experiment?)" if timed_out else f"rc={rc}")
            rec = {"exp": name, "error": err, "attempt": attempt,
                   "wall_s": wall}
            if not timed_out:
                rec["tb"] = "; ".join(errout.strip().splitlines()[-4:])
            # a wedge window can swallow several points in a row, so a
            # failed point goes to the BACK of the queue for up to
            # MAX_ATTEMPTS total tries — later is better than sooner
            # when the failure mode recovers on its own
            if attempt < MAX_ATTEMPTS:
                rec["requeued"] = True
                todo.append((name, argv, timeout, attempt + 1))
            emit(rec)
            continue
        # forward every JSON line the experiment printed; non-JSON
        # stdout (bench_attention prints a table) is wrapped verbatim
        got = False
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                rec = {"exp": name, "text": line}
            else:
                rec.setdefault("exp", name)
            emit(rec)
            got = True
        emit({"event": "done", "name": name, "wall_s": wall,
              "produced_output": got})

    emit({"event": "queue_done", "ts": time.time()})
    sink.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
