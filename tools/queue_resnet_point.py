"""One ResNet-50 ladder point for the on-chip experiment queue.

Times the jitted BSP train step at a single (steps_per_call, batch,
stem) coordinate and prints ONE JSON line in the schema
``tools/harvest_queue.py`` ingests (``exp=resnet50``).  Run by
``tools/run_tpu_queue.py`` as a subprocess so a wedged tunnel kills
only this point, not the queue.

Usage:
    python tools/queue_resnet_point.py --k 4 --batch 256 --stem s2d
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))  # repo root: theanompi_tpu
sys.path.insert(0, _TOOLS)                   # _bootstrap

import _bootstrap  # noqa: F401,E402  (makes JAX_PLATFORMS effective)
import jax  # noqa: E402
import numpy as np  # noqa: E402


def fenced_loss(metrics) -> float:
    """Value readback — the only fence the axon tunnel honors.
    Multi-step metrics come back stacked (k,); fence on the last."""
    return float(np.asarray(metrics["loss"]).ravel()[-1])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=1, help="steps_per_call")
    ap.add_argument("--batch", type=int, default=128, help="per-chip")
    ap.add_argument("--stem", default="conv7", choices=("conv7", "s2d"))
    ap.add_argument("--steps", type=int, default=32,
                    help="timed training iterations (k-dispatch rounded)")
    ap.add_argument("--crop", type=int, default=224,
                    help="input crop; shrink for off-chip wiring checks "
                    "(ResNet-50 is fully convolutional + global pool)")
    ap.add_argument("--xla-flags", default=None,
                    help="appended to XLA_FLAGS before first backend "
                    "use — the round-5 MFU queue sweeps "
                    "--xla_tpu_scoped_vmem_limit_kib here (the account "
                    "shows 1.4 ms/step of MSA prefetch stalls and "
                    "conv fusions at 93%% of HBM roofline; more scoped "
                    "VMEM is the public lever for both)")
    ap.add_argument("--buckets", type=int, default=1,
                    help="ModelConfig.exchange_buckets — the ISSUE 13 "
                    "bucketed backward/exchange interleaving lever "
                    "(run with the latency-hiding scheduler flag: the "
                    "per-bucket collectives only overlap backward "
                    "compute when the scheduler is allowed to move "
                    "them)")
    ap.add_argument("--trace", default=None,
                    help="dump a jax.profiler trace of 3 steady-state "
                    "dispatches to this dir (the bucketed A/B pair "
                    "profiles through the SAME k-cadence harness the "
                    "ladder times)")
    args = ap.parse_args()
    if args.xla_flags:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " " + args.xla_flags)
    store = max(256, args.crop + 32) if args.crop >= 224 \
        else args.crop + args.crop // 4

    from theanompi_tpu.models.base import (ModelConfig,
                                           _stack_host_batches)
    from theanompi_tpu.models.resnet50 import ResNet50
    from theanompi_tpu.data.imagenet import ImageNet_data
    from theanompi_tpu.parallel.mesh import data_mesh, shard_batch

    devices = jax.devices()
    n_chips = len(devices)
    mesh = data_mesh(n_chips, devices)
    global_batch = args.batch * n_chips

    class PointResNet50(ResNet50):
        def build_data(self):
            return ImageNet_data(crop=args.crop,
                                 synthetic_n=global_batch * args.k,
                                 synthetic_pool=8, synthetic_store=store,
                                 augment_on_device=True)

    cfg = ModelConfig(batch_size=args.batch, compute_dtype="bfloat16",
                      steps_per_call=args.k, resnet_stem=args.stem,
                      track_top5=False, print_freq=10**9,
                      exchange_buckets=args.buckets,
                      # this harness replays ONE staged batch through
                      # every dispatch; donation would delete it after
                      # the first (bench.py has the same opt-out)
                      donate_batch=False)
    model = PointResNet50(config=cfg, mesh=mesh, verbose=False)
    model.compile_iter_fns("avg")

    host_it = model.data.train_batches(0, global_batch)
    if args.k > 1:
        stacked = _stack_host_batches(host_it, args.k)
        staged = shard_batch(next(stacked), mesh,
                             spec=model.stacked_batch_spec())
        step_fn = model.train_step_multi
    else:
        staged = shard_batch(next(host_it), mesh)
        step_fn = model.train_step

    rng = jax.random.key(0)
    state = model.state
    t0 = time.perf_counter()
    state, metrics = step_fn(state, staged, rng)
    fenced_loss(metrics)
    compile_s = time.perf_counter() - t0
    for _ in range(2):  # settle to steady state
        state, metrics = step_fn(state, staged, rng)
    fenced_loss(metrics)

    n_disp = max(1, args.steps // args.k)
    t0 = time.perf_counter()
    for _ in range(n_disp):
        state, metrics = step_fn(state, staged, rng)
    loss = fenced_loss(metrics)
    dt = time.perf_counter() - t0
    assert np.isfinite(loss), f"non-finite loss {loss}"

    if args.trace:
        with jax.profiler.trace(args.trace):
            for _ in range(3):
                state, metrics = step_fn(state, staged, rng)
            fenced_loss(metrics)

    per_chip = n_disp * args.k * global_batch / dt / n_chips
    print(json.dumps({
        # a shrunken-crop wiring check must never enter the ladder
        # table harvest_queue builds from exp=="resnet50" rows
        "exp": "resnet50" if args.crop == 224 else "resnet50_wiring",
        "crop": args.crop,
        "steps_per_call": args.k,
        "batch_per_chip": args.batch,
        "stem": args.stem,
        "exchange_buckets": args.buckets,
        "img_per_sec_per_chip": round(per_chip, 2),
        "step_ms": round(dt / (n_disp * args.k) * 1e3, 2),
        "dispatch_ms": round(dt / n_disp * 1e3, 2),
        "compile_s": round(compile_s, 1),
        "loss": round(loss, 4),
        "xla_flags": args.xla_flags or "",
        "backend": jax.default_backend(),
    }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
