"""Sustained IN-SESSION ingest proof (round-4 verdict #4 / SURVEY §2.9).

The r3 host-pipeline number (4.0–4.3k img/s from mmap shards,
``tools/host_pipeline_probe.py``) was an assembly-only probe over a
small shard set — i.e. page-cache warm, no training running.  This
probe answers the open question: what does the SAME loader sustain
*while a real BSP training session runs*, over a shard set read cold?

Design (and what it does/doesn't claim):

- Generates a multi-GB tree of real mmap ``train_*.x.npy`` shards
  (store 256x256x3 uint8 — the prep default written by
  ``prepare_imagenet_shards``), large enough that a cold epoch cannot
  be served from page cache, then **drops the page cache** before the
  cold epoch (needs root; skipped with a warning otherwise).
- Runs a REAL session: the rule-API spine (model.compile_iter_fns /
  begin_epoch / train_iter / Recorder) on the 8-virtual-device CPU
  mesh, `augment_on_device=True` so the host does exactly what it does
  when feeding a chip: mmap-read + shuffle + assemble raw uint8
  batches.
- The MODEL is tiny (crop 32, width-8 1-block ResNet) **by design**:
  this box has one CPU core, so a full 224 ResNet step would make the
  session compute-bound and the loader trivially "keep up" at 50
  img/s, proving nothing.  With the device step nearly free, the
  session is loader-bound and its wall-clock img/s IS the sustained
  in-session ingest rate.  The device-side path at full 224 is proven
  on-chip separately (bench.py e2e leg; BASELINE.md).  The HOST cost
  is unchanged by the tiny model: full store-size images stream from
  disk through concatenate/shuffle/assembly either way.
- Epoch 0 runs cold (page cache dropped), epoch 1+ warm.  The cold
  epoch measures pipeline-over-disk; the warm epochs measure the
  pipeline ceiling with storage out of the picture (a stand-in for
  hosts with NVMe-class disks: this box's vda reads ~0.28 GB/s cold,
  and 2 500 img/s at 256² store needs 0.48 GB/s — **no pipeline can
  hit the north-star number from THIS disk cold**; the committed
  claim is pipeline efficiency vs the disk bound, plus the warm
  absolute rate).

Emits one JSON line per epoch:
  {"epoch": N, "cold": bool, "images": N, "wall_s": s,
   "img_per_sec": r, "disk_gb_per_sec": g, "load_s": s, "calc_s": s,
   "pipeline_efficiency_vs_disk": f}

Usage:
    python tools/ingest_session_probe.py --gb 16 --epochs 3 \
        [--tree /root/ingest_shards] [--keep-tree]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time

STORE = 256
SHARD_IMGS = 2048
BYTES_PER_IMG = STORE * STORE * 3


def build_tree(tree: str, target_gb: float) -> int:
    """Write train_*.x.npy/.y.npy shards until ~target_gb; returns the
    image count.  One random block is reused across shards (the disk
    doesn't care; npy is uncompressed) so generation runs at write
    speed, not RNG speed."""
    import numpy as np

    os.makedirs(tree, exist_ok=True)
    n_shards = max(2, int(target_gb * 1e9 / (SHARD_IMGS * BYTES_PER_IMG)))
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(SHARD_IMGS, STORE, STORE, 3),
                     dtype=np.uint8)
    manifest = {}
    t0 = time.time()
    for i in range(n_shards):
        np.save(os.path.join(tree, f"train_{i:04d}.x.npy"), x)
        y = rng.integers(0, 1000, size=SHARD_IMGS).astype(np.int64)
        np.save(os.path.join(tree, f"train_{i:04d}.y.npy"), y)
        manifest[f"train_{i:04d}.x.npy"] = SHARD_IMGS
    # one tiny val shard so the Dataset finds a val split
    np.save(os.path.join(tree, "val_0000.x.npy"), x[:256])
    np.save(os.path.join(tree, "val_0000.y.npy"),
            rng.integers(0, 1000, size=256).astype(np.int64))
    manifest["val_0000.x.npy"] = 256
    with open(os.path.join(tree, "manifest.json"), "w") as fh:
        json.dump(manifest, fh)
    os.sync()
    print(f"# built {n_shards} shards "
          f"({n_shards * SHARD_IMGS * BYTES_PER_IMG / 1e9:.1f} GB) "
          f"in {time.time() - t0:.0f}s", file=sys.stderr)
    return n_shards * SHARD_IMGS


def drop_caches() -> bool:
    try:
        subprocess.run(["sh", "-c", "sync; echo 3 > /proc/sys/vm/drop_caches"],
                       check=True, capture_output=True)
        return True
    except (subprocess.CalledProcessError, PermissionError):
        print("# WARNING: cannot drop page caches (not root?) — the "
              "'cold' epoch below may be cache-warm", file=sys.stderr)
        return False


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--gb", type=float, default=16.0)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--tree", default="/root/ingest_shards")
    ap.add_argument("--batch-per-shard", type=int, default=64)
    ap.add_argument("--keep-tree", action="store_true")
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count="
                                 f"{args.devices}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax.numpy as jnp

    from theanompi_tpu.data.imagenet import ImageNet_data
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.resnet50 import ResNet, ResNet50
    from theanompi_tpu.parallel.mesh import MeshSpec, make_training_mesh
    from theanompi_tpu.utils.recorder import Recorder

    if not os.path.isdir(args.tree) or not any(
            f.endswith(".x.npy") for f in os.listdir(args.tree)):
        build_tree(args.tree, args.gb)

    tree = args.tree

    class IngestRN(ResNet50):
        def build_data(self):
            return ImageNet_data(data_dir=tree, crop=32,
                                 augment_on_device=True)

        def build_module(self):
            return ResNet(stage_sizes=(1,), width=8,
                          n_classes=self.data.n_classes,
                          dtype=jnp.float32, bn_axis=self._bn_axis())

    mesh = make_training_mesh(MeshSpec(data=args.devices),
                              jax.devices()[:args.devices])
    cfg = ModelConfig(batch_size=args.batch_per_shard, sync_bn=True,
                      n_epochs=args.epochs, compute_dtype="float32",
                      print_freq=10**9)
    model = IngestRN(config=cfg, mesh=mesh, verbose=False)
    model.compile_iter_fns("avg")
    global_batch = model.global_batch

    for epoch in range(args.epochs):
        cold = epoch == 0 and drop_caches()
        rec = Recorder(rank=1, size=args.devices, print_freq=10**9)
        n_iters = model.begin_epoch(epoch)
        t0 = time.perf_counter()
        it = 0
        while it < n_iters:
            it += model.train_iter(it, rec)
        model._flush_metrics(rec)
        wall = time.perf_counter() - t0
        images = it * global_batch
        gbps = images * BYTES_PER_IMG / wall / 1e9
        sections = {k: round(float(rec.epoch_time.get(k, 0.0)), 2)
                    for k in rec.SECTIONS}
        ld = model._train_prefetcher.stats
        loader_rate = (ld["images"] / ld["busy_s"]
                       if ld["busy_s"] else 0.0)
        print(json.dumps({
            "epoch": epoch, "cold": cold, "images": images,
            "wall_s": round(wall, 2),
            "img_per_sec": round(images / wall, 1),
            # the loader's own critical path (assembly + sharded
            # device_put, timed inside the prefetch thread): what it
            # sustains independent of the consumer — on a CPU mesh the
            # "device" step shares the one host core, so session wall
            # rate under-reports the loader
            "loader_img_per_sec": round(loader_rate, 1),
            "loader_busy_s": round(ld["busy_s"], 2),
            "disk_gb_per_sec": round(gbps, 3),
            **sections,
            "global_batch": global_batch,
            "store": STORE,
        }), flush=True)
    model.cleanup()
    if not args.keep_tree:
        shutil.rmtree(tree, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
