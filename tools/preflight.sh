#!/bin/bash
# Snapshot gate (round-4 verdict #6 / round-3 #4b): the FULL suite —
# slow tests included — plus the driver entry points must be green
# before any end-of-round snapshot.  Round 3 committed a slow e2e test
# that had never been run (it failed); nothing structural prevented a
# repeat until this script.
#
# Usage:  bash tools/preflight.sh [artifacts/preflight_rNN.log]
# Exit 0 = safe to snapshot.  Writes the full output to the log path
# (default artifacts/preflight.log) so the round log can cite it.
set -u
LOG="${1:-artifacts/preflight.log}"
cd "$(dirname "$0")/.."
# shm-lane evidence scan (ISSUE 20): the same-host smokes must show
# the shared-memory lane actually carried payload — a grant landed
# AND out-of-band bytes flowed — in the monitor JSONL the smoke just
# wrote.  Returns 1 (and prints what's missing) if the lane silently
# fell back everywhere, which would mean the negotiation or adopter
# wiring regressed while the in-band fallback kept the smoke green.
shm_lane_evidence() {  # $1 = monitor dir, $2 = plane label
  python - "$1" "$2" <<'PYEOF'
import glob, json, os, sys
mondir, label = sys.argv[1], sys.argv[2]
grants = oob = 0.0
for path in glob.glob(os.path.join(mondir, "**", "*.jsonl"),
                      recursive=True):
    for line in open(path):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        # role exporters write flat per-series records; the collector
        # wraps a snapshot list inside event=metrics records
        series = [rec] if "name" in rec else rec.get("snapshot") or []
        for s in series:
            if s.get("name") == "shm/grants_total":
                grants = max(grants, s.get("value") or 0.0)
            elif s.get("name") == "shm/oob_bytes_total":
                oob = max(oob, s.get("value") or 0.0)
if grants < 1 or oob <= 0:
    print(f"shm lane evidence MISSING for {label}: "
          f"grants={grants:.0f} oob_bytes={oob:.0f}")
    sys.exit(1)
print(f"shm lane evidence ({label}): grants>={grants:.0f}, "
      f"{oob/1e6:.3f} MB out-of-band")
PYEOF
}
{
  echo "# preflight $(date -u +%Y-%m-%dT%H:%M:%SZ) HEAD=$(git rev-parse --short HEAD)"
  echo "## tmlint --gate (static checker suite, docs/ANALYSIS.md)"
  # zero NEW findings vs analysis/baseline.json; pure-ast, seconds on
  # CPU — runs FIRST so a locking/donation/doc-drift regression fails
  # before the expensive suites even start
  python tools/tmlint.py --gate
  TMLINT_RC=$?
  echo "tmlint rc=$TMLINT_RC"
  echo "## pytest slow-subset gate (-m gate)"
  # The tagged MUST-PASS slow subset (pyproject markers: 'gate') runs
  # as its OWN step so an environmental failure elsewhere in the full
  # --runslow set (e.g. this jax's multihost-on-CPU limitation) can
  # never mask a broken gate test — the round-3 failure mode was a
  # committed-but-never-run slow e2e, and a habitually-red full suite
  # recreates exactly that blind spot.  Currently gated: the jpeg-tree
  # end-to-end training oracle (tests/test_oracle.py).
  python -m pytest tests/ --runslow -q -m gate
  GATE_RC=$?
  echo "gate subset rc=$GATE_RC"
  echo "## pytest --runslow (-m 'not gate' — the gate subset just ran)"
  python -m pytest tests/ --runslow -q -m 'not gate'
  PYTEST_RC=$?
  echo "pytest rc=$PYTEST_RC"
  echo "## __graft_entry__ (entry + dryrun_multichip on the virtual mesh)"
  # CPU-forced: a wedged axon tunnel must not hang the gate (the
  # driver compile-checks entry() on the real chip separately)
  THEANOMPI_TPU_ENTRY_CPU=1 python __graft_entry__.py
  ENTRY_RC=$?
  echo "graft_entry rc=$ENTRY_RC"
  echo "## monitor smoke (5-step CPU BSP with THEANOMPI_TPU_MONITOR)"
  # telemetry end-to-end: the snapshot JSONL must parse and carry the
  # core series, and the heartbeat must be fresh (docs/OBSERVABILITY.md)
  MONDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu THEANOMPI_TPU_MONITOR="$MONDIR" python - <<'PYEOF'
import json, os, sys, time
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
from theanompi_tpu.data.cifar10 import Cifar10_data
from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.models.cifar10 import Cifar10_model
from theanompi_tpu.parallel import data_mesh
from theanompi_tpu.rules.bsp import run_bsp_session

class Tiny(Cifar10_model):
    def build_data(self):
        return Cifar10_data(synthetic_n=80)  # 5 iters at batch 2 x 8

cfg = ModelConfig(batch_size=2, n_epochs=1, print_freq=10**9,
                  compute_dtype="float32")
run_bsp_session(Tiny(config=cfg, mesh=data_mesh(8)), max_epochs=1,
                checkpoint=False)
mondir = os.environ["THEANOMPI_TPU_MONITOR"]
recs = [json.loads(l)
        for l in open(os.path.join(mondir, "metrics_rank0.jsonl"))]
names = {r["name"] for r in recs}
missing = {"step_ms", "span_ms", "recorder/section_ms"} - names
assert not missing, f"snapshot missing core series: {missing}"
steps = next(r for r in recs if r["name"] == "step_ms")
assert steps["count"] == 5, f"expected 5 step observations: {steps}"
hb = json.load(open(os.path.join(mondir, "heartbeat_rank0.json")))
assert time.time() - hb["written"] < 120, f"stale heartbeat: {hb}"
assert hb["stalled"] is False
print(f"monitor smoke OK: {len(names)} series, "
      f"step p50 {steps['p50']:.1f}ms, heartbeat fresh")
PYEOF
  MONITOR_RC=$?
  rm -rf "$MONDIR"
  echo "monitor smoke rc=$MONITOR_RC"
  echo "## collector smoke (distributed tracing: trainer -> 2 real shard processes + concurrent decode GENERATE -> one collector, docs/OBSERVABILITY.md 'Distributed tracing')"
  # the ISSUE 16 vertical end-to-end: a supervised collector process, a
  # REAL 2-shard EASGD fleet, and a concurrent decode GENERATE, all
  # shipping span/metric events to ONE merged fleet.jsonl.  The gate
  # asserts (a) the exchange reconstructs as a single trace spanning
  # >= 3 PROCESSES with zero orphans, (b) the GENERATE reconstructs as
  # a single client->rpc_handle->decode_generate trace, (c)
  # tools/traces.py prints the critical path and runs the
  # idle-all-workers gap detector on the merged stream, and (d)
  # tools/tmtop.py renders a fleet frame from the shipped metrics
  COLDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu THEANOMPI_TPU_MONITOR="$COLDIR" python - <<'PYEOF'
import os, socket, sys, threading, time
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
os.environ["THEANOMPI_TPU_TRACE"] = "1"  # before any child spawns
from theanompi_tpu import monitor
from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.models.transformer import TransformerLM
from theanompi_tpu.monitor.collector import CollectorProcess
from theanompi_tpu.parallel.shards import (ShardProcessGroup,
                                           ShardedEASGD,
                                           shard_addresses)
from theanompi_tpu.serving import (InferenceClient, InferenceServer,
                                   export_model, serve)

mondir = os.environ["THEANOMPI_TPU_MONITOR"]
col = CollectorProcess(mondir)  # exports THEANOMPI_TPU_COLLECTOR
group = ShardProcessGroup(2, max_restarts=1)  # inherits trace+collector
try:
    cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                      compute_dtype="float32", optimizer="adamw",
                      learning_rate=1e-3, weight_decay=0.0,
                      lr_schedule="constant")
    lm = TransformerLM(config=cfg, vocab=32, seq_len=16, n_layers=1,
                       d_model=16, n_heads=2, verbose=False)
    export_dir = os.path.join(mondir, "export")
    export_model(lm, export_dir, version=0)
    rng = np.random.default_rng(0)
    tree = {"a": rng.standard_normal((64, 8)).astype(np.float32),
            "b": rng.standard_normal((33,)).astype(np.float32)}
    with monitor.session(run_dir=mondir, stall_after=float("inf")):
        server = InferenceServer(
            export_dir, replicas=1, reload_poll_s=0, model=lm,
            decode=True,
            decode_opts=dict(page_size=4, pages_per_seq=8, max_seqs=4,
                             prefill_buckets=(8,))).start()
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        ready = threading.Event()
        t = threading.Thread(target=serve,
                             args=(server, "127.0.0.1", port, ready),
                             daemon=True)
        t.start()
        assert ready.wait(30)
        c = InferenceClient(f"127.0.0.1:{port}")
        gen_out = {}

        def gen():
            with monitor.span("client_generate"):
                gen_out["toks"] = c.generate(
                    np.asarray([1, 2, 3], np.int32), 6)

        gt = threading.Thread(target=gen)
        gt.start()  # concurrent with the exchange leg, per the gate
        srv = ShardedEASGD(shard_addresses(group.server_addr), tree,
                           alpha=0.5, session_id="preflight-trace")
        for n in range(3):
            w = jax.tree.map(lambda x: x + np.float32(0.05 * (n + 1)),
                             tree)
            with monitor.span("exchange_period"):
                srv.exchange(w)
        srv.close()
        gt.join(120)
        assert gen_out.get("toks") is not None \
            and len(gen_out["toks"]) == 6
        c.shutdown()
        c.close()
        t.join(timeout=5)
        server.stop()
        time.sleep(1.5)  # let the shard exporters flush their tails
    # session exit flushed the trainer's exporter; the fleet file now
    # carries >= 3 processes (trainer + 2 shards) + the collector meta
    st = col.stats()
    assert st and st["events"] > 0 and st["senders"] >= 3, st
    sys.path.insert(0, os.path.join(os.getcwd(), "tools"))
    import traces as traces_tool
    records = traces_tool.load_events(os.path.join(mondir,
                                                   "fleet.jsonl"))
    tr = traces_tool.assemble(records)
    ex = [s for s in tr.values()
          if any(x["name"] == "exchange_period" for x in s)]
    assert ex, "no exchange trace reached the collector"
    stitched = [s for s in ex
                if len(traces_tool.processes_of(s)) >= 3
                and not traces_tool.orphans(s)]
    assert stitched, [
        (len(s), sorted(traces_tool.processes_of(s)),
         len(traces_tool.orphans(s))) for s in ex]
    gen_tr = [s for s in tr.values()
              if any(x["name"] == "client_generate" for x in s)]
    assert len(gen_tr) == 1 and not traces_tool.orphans(gen_tr[0]), \
        "GENERATE must reconstruct as ONE trace with zero orphans"
    names = [x["name"] for x in gen_tr[0]]
    assert any("rpc_handle" in n for n in names), names
    assert any("decode_generate" in n for n in names), names
    print(f"collector smoke OK: {st['events']} events from "
          f"{st['senders']} senders, exchange trace spans "
          f"{len(traces_tool.processes_of(stitched[0]))} processes "
          f"({len(stitched[0])} spans, 0 orphans), GENERATE stitched "
          f"({len(gen_tr[0])} spans)")
finally:
    group.stop()
    col.stop()
PYEOF
  COLLECTOR_RC=$?
  if [ "$COLLECTOR_RC" -eq 0 ]; then
    # the consumer tools over the SAME merged file: traces.py must
    # confirm a >=3-process orphan-free trace, print its critical
    # path, and run the idle-gap detector; tmtop must render a frame
    python tools/traces.py "$COLDIR" --require-procs 3 --gap-ms 5000 \
      > "$COLDIR/traces.out" 2>&1
    TRACES_RC=$?
    grep -q "critical path" "$COLDIR/traces.out" || TRACES_RC=1
    grep -q "idle-all-workers gaps" "$COLDIR/traces.out" || TRACES_RC=1
    sed -n '1,12p' "$COLDIR/traces.out"
    python tools/tmtop.py "$COLDIR" --once || TRACES_RC=1
    COLLECTOR_RC=$TRACES_RC
  fi
  rm -rf "$COLDIR"
  echo "collector smoke rc=$COLLECTOR_RC"
  echo "## resilience smoke (EASGD kill-and-recover via THEANOMPI_TPU_FAULTS)"
  # fault-injection end-to-end (docs/RESILIENCE.md): kill worker 1 at
  # step 3 of a tiny EASGD session; supervised recovery must restart
  # it from center, the run must exit 0, and the recovery event must
  # land in the monitor JSONL
  FAULTDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu THEANOMPI_TPU_MONITOR="$FAULTDIR" \
    THEANOMPI_TPU_FAULTS='[{"site": "worker_step", "rule": "easgd", "worker": 1, "step": 3}]' \
    python - <<'PYEOF'
import json, os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from theanompi_tpu import EASGD
from theanompi_tpu.models.base import ModelConfig

cfg = ModelConfig(batch_size=8, n_epochs=1, learning_rate=0.01,
                  snapshot_dir=os.environ["THEANOMPI_TPU_MONITOR"],
                  print_freq=0)
rule = EASGD()
rule.init(devices=2, modelfile="tests._tiny_models",
          modelclass="TinyCifar", config=cfg, tau=4, alpha=0.5,
          checkpoint=False, max_restarts=1)
res = rule.wait()
assert res["restarts"] == {1: 1}, res.get("restarts")
assert res["lost_workers"] == [], res.get("lost_workers")
assert np.isfinite(res["val"]["loss"])
mondir = os.environ["THEANOMPI_TPU_MONITOR"]
recs = [json.loads(l)
        for l in open(os.path.join(mondir, "metrics_rank0.jsonl"))]
by_name = {r["name"]: r for r in recs}
assert "resilience/worker_restarts_total" in by_name, sorted(by_name)
assert "resilience/faults_injected_total" in by_name
print("resilience smoke OK: worker 1 killed at step 3, restarted "
      "from center, recovery event in monitor JSONL")
PYEOF
  RESILIENCE_RC=$?
  rm -rf "$FAULTDIR"
  echo "resilience smoke rc=$RESILIENCE_RC"
  echo "## serving smoke (export -> server -> concurrent clients, docs/SERVING.md)"
  # the serving vertical end-to-end on CPU: export an untrained tiny
  # model, serve it on a real socket, fire concurrent clients; at
  # least one multi-request batch must form and the request-latency
  # histogram must land in the monitor JSONL
  SERVEDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu THEANOMPI_TPU_MONITOR="$SERVEDIR" python - <<'PYEOF'
import glob, json, os, socket, threading
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tests._tiny_models import TinyCifar
from theanompi_tpu import monitor
from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.serving import (BatchPolicy, InferenceClient,
                                   InferenceServer, export_model, serve)

mondir = os.environ["THEANOMPI_TPU_MONITOR"]
model = TinyCifar(config=ModelConfig(batch_size=8, n_epochs=1,
                                     print_freq=0), verbose=False)
export_dir = os.path.join(mondir, "export")
export_model(model, export_dir, version=0)
with monitor.session(run_dir=mondir, stall_after=float("inf")):
    server = InferenceServer(
        export_dir, replicas=1, reload_poll_s=0, model=model,
        policy=BatchPolicy(max_batch=4, max_delay_ms=50.0,
                           buckets=(4,), max_queue=16)).start()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    ready = threading.Event()
    t = threading.Thread(target=serve,
                         args=(server, "127.0.0.1", port, ready),
                         daemon=True)
    t.start()
    assert ready.wait(30)
    x = np.asarray(model.data.x_val[:8])
    outs = [None] * 8
    clients = [InferenceClient(f"127.0.0.1:{port}") for _ in range(8)]
    ths = [threading.Thread(
        target=lambda i=i: outs.__setitem__(
            i, clients[i].infer(x[i:i + 1]))) for i in range(8)]
    for th in ths:
        th.start()
    for th in ths:
        th.join()
    st = clients[0].stats()
    assert st["max_occupancy"] > 1, f"no dynamic batch formed: {st}"
    assert all(o is not None and o.shape == (1, 10) for o in outs)
    clients[0].shutdown()
    for c in clients:
        c.close()
    t.join(timeout=5)
    server.stop()
snap = [p for p in glob.glob(os.path.join(mondir, "metrics_rank0.jsonl"))]
recs = [json.loads(l) for l in open(snap[0])]
names = {r["name"] for r in recs}
missing = {"serving/request_ms", "serving/batch_occupancy",
           "serving/requests_total"} - names
assert not missing, f"snapshot missing serving series: {missing}"
lat = next(r for r in recs if r["name"] == "serving/request_ms")
assert lat["count"] == 8 and "p99" in lat, lat
print(f"serving smoke OK: occupancy_max={st['max_occupancy']}, "
      f"{st['batches']} batches / {st['rows']} rows, "
      f"request p99 {lat['p99']:.1f}ms in monitor JSONL")
PYEOF
  SERVING_RC=$?
  rm -rf "$SERVEDIR"
  echo "serving smoke rc=$SERVING_RC"
  echo "## decode smoke (LM+draft exports -> speculative decode server -> shared-prefix streams, docs/SERVING.md 'Decode'/'Speculative decode'/'Prefix cache')"
  # the autoregressive vertical end-to-end on CPU: export a tiny
  # TransformerLM AND a bf16 self-draft, serve in decode mode with
  # speculation + prefix cache on a real socket, drive a warm stream
  # then two concurrent streams sharing its page-aligned prompt
  # prefix; at least one decode step must batch rows from BOTH
  # sequences (iteration-level sharing), every stream must match the
  # uncached full-forward argmax oracle, speculation must accept at
  # least one draft (accept-rate > 0), the prefix-cache hit counter
  # must land in the monitor JSONL, and the inter-token histogram too
  DECODEDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu THEANOMPI_TPU_MONITOR="$DECODEDIR" python - <<'PYEOF'
import json, os, socket, threading
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from theanompi_tpu import monitor
from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.models.transformer import TransformerLM
from theanompi_tpu.serving import (InferenceClient, InferenceServer,
                                   export_model, serve)

mondir = os.environ["THEANOMPI_TPU_MONITOR"]
cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                  compute_dtype="float32", optimizer="adamw",
                  learning_rate=1e-3, weight_decay=0.0,
                  lr_schedule="constant")
model = TransformerLM(config=cfg, vocab=32, seq_len=16, n_layers=2,
                      d_model=16, n_heads=2, verbose=False)
params = jax.device_get(model.state.params)
export_dir = os.path.join(mondir, "export")
draft_dir = os.path.join(mondir, "draft")
export_model(model, export_dir, version=0)
# bf16 self-draft: same net quantized — near-total greedy agreement,
# so the accept machinery is exercised without a training run
export_model(model, draft_dir, version=0, weight_dtype="bf16")
with monitor.session(run_dir=mondir, stall_after=float("inf")):
    server = InferenceServer(
        export_dir, replicas=1, reload_poll_s=0, model=model,
        decode=True,
        decode_opts=dict(page_size=4, pages_per_seq=8, max_seqs=4,
                         prefill_buckets=(8,),
                         draft_export_dir=draft_dir,
                         speculate_k=3)).start()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    ready = threading.Event()
    t = threading.Thread(target=serve,
                         args=(server, "127.0.0.1", port, ready),
                         daemon=True)
    t.start()
    assert ready.wait(30)
    rng = np.random.default_rng(0)
    base = rng.integers(0, 32, 4).astype(np.int32)   # shared page
    warm_prompt = np.concatenate(
        [base, rng.integers(0, 32, 1).astype(np.int32)])
    prompts = [np.concatenate(
        [base, rng.integers(0, 32, n).astype(np.int32)])
        for n in (2, 3)]
    def oracle(p, n):
        cur = [int(x) for x in p]
        out = []
        for _ in range(n):
            lg = np.asarray(model.module.apply(
                {"params": params}, jnp.asarray([cur], jnp.int32),
                train=False, seq_axis=None))
            tok = int(np.argmax(lg[0, -1])); out.append(tok)
            cur.append(tok)
        return out
    clients = [InferenceClient(f"127.0.0.1:{port}") for _ in range(2)]
    # warm stream completes first: registers the shared prefix so the
    # concurrent pair deterministically hits it
    warm_out = clients[0].generate(warm_prompt, 10)
    assert list(warm_out) == oracle(warm_prompt, 10)
    outs = [None, None]
    ths = [threading.Thread(
        target=lambda i=i: outs.__setitem__(
            i, clients[i].generate(prompts[i], 10))) for i in range(2)]
    for th in ths:
        th.start()
    for th in ths:
        th.join(120)
    # every stream token-identical to the uncached flax oracle
    for p, o in zip(prompts, outs):
        assert o is not None and list(o) == oracle(p, 10), (o, p)
    st = clients[0].stats()
    assert st["decode"] is True
    assert st["shared_steps"] >= 1, f"no shared decode step: {st}"
    assert st["accept_rate"] and st["accept_rate"] > 0, \
        f"speculation accepted nothing: {st}"
    assert st["prefix_cache_hits"] >= 1, f"no prefix hit: {st}"
    clients[0].shutdown()
    for c in clients:
        c.close()
    t.join(timeout=5)
    server.stop()
recs = [json.loads(l)
        for l in open(os.path.join(mondir, "metrics_rank0.jsonl"))]
names = {r["name"] for r in recs}
missing = {"decode/intertoken_ms", "decode/tokens_total",
           "decode/steps_total", "decode/accept_rate",
           "decode/draft_tokens_total",
           "decode/prefix_cache_hits_total"} - names
assert not missing, f"snapshot missing decode series: {missing}"
itl = next(r for r in recs if r["name"] == "decode/intertoken_ms")
# 3 streams x 10 tokens, minus each stream's FIRST token (prefill's
# output: queue+prefill latency, excluded from the inter-token SLO);
# rejected draft tokens never enter the histogram either
assert itl["count"] == 27 and "p99" in itl, itl
hits = next(r for r in recs
            if r["name"] == "decode/prefix_cache_hits_total")
assert hits["value"] >= 1, hits
print(f"decode smoke OK: shared_steps={st['shared_steps']}, "
      f"{st['tokens']} tokens / {st['steps']} steps, "
      f"accept_rate {st['accept_rate']:.2f}, "
      f"prefix hits {st['prefix_cache_hits']}, "
      f"intertoken p99 {itl['p99']:.1f}ms in monitor JSONL")
PYEOF
  DECODE_RC=$?
  rm -rf "$DECODEDIR"
  echo "decode smoke rc=$DECODE_RC"
  echo "## frontdoor smoke (disaggregated fleet: router + 2 prefill + 1 decode REAL processes, docs/SERVING.md 'Disaggregated serving')"
  # the ISSUE 17 vertical end-to-end: DisaggregatedFleet spawns real
  # prefill subprocesses and a real decode subprocess, router in the
  # parent; three CONCURRENT client streams generate through the
  # front door (prompt phase on a prefill replica, pages migrated
  # over wire v2, token phase on the decode replica).  The gate
  # asserts greedy determinism across identical prompts, zero sheds,
  # and — via the collector file — that ONE client_generate trace
  # stitches >= 3 PROCESSES with zero orphans and carries the
  # page_migrate span; tools/traces.py --require-procs 3 then
  # confirms the same from the merged stream and prints the critical
  # path.  The batched-prefill additions (docs/SERVING.md "Batched
  # prefill" / "Fleet prefix cache"): concurrent streams must COALESCE
  # into a multi-sequence prefill batch (occupancy > 1 in the monitor
  # JSONL), and a prompt prefilled on the cache authority must FLEET-
  # HIT from the peer replica — shipped pages, byte-identical output,
  # zero leaked leases — instead of recomputing the prefix
  # the toy model's KV pages are ~KB-scale — far under the 64KB
  # default lane floor — so drop the floor for this smoke to prove
  # the disagg page-migration path inherits the lane end-to-end
  FRONTDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu THEANOMPI_TPU_MONITOR="$FRONTDIR" \
    THEANOMPI_TPU_SHM_MIN_BYTES=256 python - <<'PYEOF'
import os, sys, threading, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
os.environ["THEANOMPI_TPU_TRACE"] = "1"  # before any child spawns
from theanompi_tpu import monitor
from theanompi_tpu.frontdoor.fleet import DisaggregatedFleet
from theanompi_tpu.frontdoor.prefill import PrefillClient
from theanompi_tpu.frontdoor.router import RouterClient
from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.models.transformer import TransformerLM
from theanompi_tpu.monitor.collector import CollectorProcess
from theanompi_tpu.serving import export_model

mondir = os.environ["THEANOMPI_TPU_MONITOR"]
cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                  compute_dtype="float32", optimizer="adamw",
                  learning_rate=1e-3, weight_decay=0.0,
                  lr_schedule="constant")
lm = TransformerLM(config=cfg, vocab=32, seq_len=32, n_layers=2,
                   d_model=16, n_heads=2, verbose=False)
export_dir = os.path.join(mondir, "export")
export_model(lm, export_dir, version=0)
col = CollectorProcess(mondir)  # exports THEANOMPI_TPU_COLLECTOR
try:
    with monitor.session(run_dir=mondir, stall_after=float("inf")), \
         DisaggregatedFleet(export_dir, prefill=2, decode=1,
                            router_host="127.0.0.1", page_size=4,
                            pages_per_seq=8, max_seqs=4,
                            prefill_buckets=(8,),
                            prefill_delay_ms=250.0) as fleet:
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 32, 5).astype(np.int32)
                   for _ in range(2)]
        prompts.append(prompts[0].copy())  # greedy-determinism pair
        outs = [None] * 3

        def gen(i):
            c = RouterClient(fleet.router_addr)
            try:
                with monitor.span("client_generate"):
                    outs[i] = c.generate(prompts[i], 6)
            finally:
                c.close()

        ths = [threading.Thread(target=gen, args=(i,))
               for i in range(3)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(180)
        assert all(o is not None and len(o) == 6 for o in outs), outs
        assert list(outs[0]) == list(outs[2]), (outs[0], outs[2])
        c = RouterClient(fleet.router_addr)
        st = c.stats()
        c.close()
        assert st["streams"] >= 3 and st["shed"] == 0, st
        # batched prefill: 3 concurrent streams round-robin over 2
        # replicas, so ONE replica saw 2 inside the 250ms coalescing
        # window — a multi-sequence batch (fewer batches than prompts)
        addrs = fleet.prefill_group.addresses()
        pstats = []
        for a in addrs:
            pc = PrefillClient(a)
            pstats.append(pc.stats())
            pc.close()
        assert sum(s["prefills"] for s in pstats) >= 3, pstats
        assert any(s["prefills"] > s["prefill_batches"]
                   for s in pstats), \
            f"no multi-sequence prefill batch formed: {pstats}"
        # fleet prefix cache: prefill a FRESH prompt on the authority
        # (replica 0), then the SAME prompt on the peer — the peer has
        # never seen it, so its local prefix hit can only come from
        # pages the authority shipped over the wire; byte-identical
        # pages, and the lease is released (never leaked)
        auth = fleet._authority_addr
        peer = next(a for a in addrs if a != auth)
        pnew = rng.integers(0, 32, 8).astype(np.int32)
        c0, c1 = PrefillClient(auth), PrefillClient(peer)
        try:
            hits0 = c1.stats()["prefix_cache"]["hits"]
            man0, k0, v0 = c0.prefill(pnew)
            man1, k1, v1 = c1.prefill(pnew)
            assert man0["first_token"] == man1["first_token"], \
                (man0, man1)
            # the shipped PREFIX page (pages axis 1) is byte-verbatim
            # on the peer — shipped, not recomputed; suffix pages are
            # extend-computed and only token-identity is pinned
            assert np.array_equal(np.asarray(k0)[:, 0],
                                  np.asarray(k1)[:, 0])
            assert np.array_equal(np.asarray(v0)[:, 0],
                                  np.asarray(v1)[:, 0])
            st1 = c1.stats()
            assert st1["prefix_cache"]["hits"] >= hits0 + 1, \
                f"peer never fleet-hit the authority's prefix: {st1}"
            st0 = c0.stats()
            assert st0["fleet_cache_leases"] == 0, \
                f"authority leaked a fleet-cache lease: {st0}"
        finally:
            c0.close()
            c1.close()
        time.sleep(3.0)  # let the role exporters flush their tails
                         # (metric snapshots ship every ~2s)
    # the fleet file now carries client+router / prefill / decode
    cst = col.stats()
    assert cst and cst["events"] > 0 and cst["senders"] >= 3, cst
    # monitor JSONL: the batched-prefill occupancy histogram and the
    # fleet-cache hit/ship counters crossed the collector (snapshots
    # are cumulative — take the max each series ever reported)
    import json
    occ = 0.0
    fleet_hits = 0.0
    ship_bytes = 0.0
    for line in open(os.path.join(mondir, "fleet.jsonl")):
        rec = json.loads(line)
        if rec.get("event") != "metrics":
            continue
        for s in rec.get("snapshot", []):
            if s["name"] == "frontdoor/prefill_batch_occupancy":
                occ = max(occ, s.get("max") or 0.0)
            elif (s["name"] == "frontdoor/fleet_cache_lookups_total"
                  and s.get("labels", {}).get("result") == "hit"):
                fleet_hits = max(fleet_hits, s["value"])
            elif s["name"] == "decode/fleet_cache_ship_bytes_total":
                ship_bytes = max(ship_bytes, s["value"])
    assert occ > 1, \
        f"prefill_batch_occupancy max {occ} <= 1 in monitor JSONL"
    assert fleet_hits >= 1, \
        "no fleet-cache hit reached the monitor JSONL"
    assert ship_bytes > 0, \
        "fleet-cache hit shipped zero page bytes"
    sys.path.insert(0, os.path.join(os.getcwd(), "tools"))
    import traces as traces_tool
    records = traces_tool.load_events(os.path.join(mondir,
                                                   "fleet.jsonl"))
    tr = traces_tool.assemble(records)
    gen_tr = [s for s in tr.values()
              if any(x["name"] == "client_generate" for x in s)]
    assert gen_tr, "no client_generate trace reached the collector"
    full = [s for s in gen_tr
            if len(traces_tool.processes_of(s)) >= 3
            and not traces_tool.orphans(s)]
    assert full, [(len(s), sorted(traces_tool.processes_of(s)),
                   len(traces_tool.orphans(s))) for s in gen_tr]
    names = [x["name"] for x in full[0]]
    assert any("page_migrate" in n for n in names), names
    print(f"frontdoor smoke OK: {st['streams']} streams through "
          f"router+prefill+decode, stitched trace spans "
          f"{len(traces_tool.processes_of(full[0]))} processes "
          f"({len(full[0])} spans, 0 orphans, page_migrate present), "
          f"prefill batch occupancy max {occ:.0f}, "
          f"{fleet_hits:.0f} fleet-cache hit(s) shipped "
          f"{ship_bytes:.0f} page bytes")
finally:
    col.stop()
PYEOF
  FRONTDOOR_RC=$?
  if [ "$FRONTDOOR_RC" -eq 0 ]; then
    # the consumer tool over the SAME merged file: traces.py must
    # confirm the >=3-process orphan-free trace and print its
    # critical path
    python tools/traces.py "$FRONTDIR" --require-procs 3 \
      > "$FRONTDIR/traces.out" 2>&1
    FTRACES_RC=$?
    grep -q "critical path" "$FRONTDIR/traces.out" || FTRACES_RC=1
    sed -n '1,8p' "$FRONTDIR/traces.out"
    FRONTDOOR_RC=$FTRACES_RC
  fi
  # page migration + fleet-cache ship between same-host replicas must
  # have granted the lane and moved KV pages out-of-band
  if [ "$FRONTDOOR_RC" -eq 0 ]; then
    shm_lane_evidence "$FRONTDIR" "disagg kv pages" || FRONTDOOR_RC=1
  fi
  rm -rf "$FRONTDIR"
  echo "frontdoor smoke rc=$FRONTDOOR_RC"
  echo "## exchange-bench smoke (wire v1 vs v2 over real sockets, docs/DESIGN.md 'Wire protocol v2')"
  # the comms vertical end-to-end: drive the ~25M-param ResNet-50-sized
  # tree through the param service in every protocol x compression x
  # dtype mode; the gate asserts v2-framed beats v1-pickle on
  # bytes/exchange (lossless zlib/f32 AND the >=45% bf16 headline cut)
  # and that the wire compression-ratio gauge landed in the monitor
  # JSONL (tools/bench_exchange.py --smoke, exit 1 on any miss)
  EXCHDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu THEANOMPI_TPU_MONITOR="$EXCHDIR" \
    python tools/bench_exchange.py --smoke \
      --out "$EXCHDIR/BENCH_wire_smoke.json"
  EXCHANGE_RC=$?
  rm -rf "$EXCHDIR"
  echo "exchange smoke rc=$EXCHANGE_RC"
  echo "## bucketed-exchange smoke (B=4 in-step bucketing on the 8-dev CPU mesh, docs/DESIGN.md 'Bucketed exchange')"
  # the ISSUE 13 vertical: bucketed exchange programs over the
  # ResNet-50-sized tree on the 8-device CPU mesh.  The gate asserts
  # (a) a real B=4 train step is BIT-identical to B=1 over 3
  # iterations (bucketing is scheduling, never numerics) and (b) the
  # bsp/exchange_buckets gauge landed in the monitor JSONL
  # (tools/bench_exchange.py --buckets 4 --smoke, exit 1 on any miss)
  BUCKETDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu THEANOMPI_TPU_MONITOR="$BUCKETDIR" \
    python tools/bench_exchange.py --buckets 4 --smoke \
      --out "$BUCKETDIR/BENCH_bucketed_smoke.json"
  BUCKET_RC=$?
  rm -rf "$BUCKETDIR"
  echo "bucketed-exchange smoke rc=$BUCKET_RC"
  echo "## shard smoke (2-shard EASGD over real sockets + kill-recovery, docs/DESIGN.md 'Sharded parameter service')"
  # the sharded-center vertical end-to-end: two REAL shard processes,
  # the router's concurrent leaf-range exchanges, and the fault leg —
  # shard 0 is hard-killed, the process group relaunches it, and the
  # per-shard session rejoin re-seeds only its leaf range.  The gate
  # asserts the K=2 aggregate wall beats K=1, BOTH shards served
  # traffic (per-shard shard_exchange spans in the monitor JSONL), and
  # the recovery events (client reconnect + shard relaunch) landed
  SHARDDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu THEANOMPI_TPU_MONITOR="$SHARDDIR" \
    python tools/bench_exchange.py --smoke --shards 2 \
      --out "$SHARDDIR/BENCH_shard_smoke.json"
  SHARD_RC=$?
  # same-host shards: the shm lane must have granted and carried the
  # exchange payload out-of-band (docs/DESIGN.md 'Shared-memory lane')
  if [ "$SHARD_RC" -eq 0 ]; then
    shm_lane_evidence "$SHARDDIR" "shard exchange" || SHARD_RC=1
  fi
  rm -rf "$SHARDDIR"
  echo "shard smoke rc=$SHARD_RC"
  echo "## hierarchy smoke (4 local workers -> 1 aggregator -> 2 real shard processes, docs/DESIGN.md 'Hierarchical exchange')"
  # the ISSUE 14 vertical: intra-host aggregation in front of a real
  # 2-shard fleet.  The gate asserts wire bytes/period land FLAT in N
  # (>= 3.9x below the 4-worker direct-exchange baseline), the ASGD
  # delta-sum byte-identity + EASGD closed-form trajectory pins, and
  # the monitor evidence — aggregate/fan_in gauge at 4 and
  # local_aggregate spans in the JSONL
  # (tools/bench_exchange.py --local-workers 4 --shards 2 --smoke)
  HIERDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu THEANOMPI_TPU_MONITOR="$HIERDIR" \
    python tools/bench_exchange.py --local-workers 4 --shards 2 \
      --smoke --out "$HIERDIR/BENCH_hierarchy_smoke.json"
  HIER_RC=$?
  rm -rf "$HIERDIR"
  echo "hierarchy smoke rc=$HIER_RC"
  echo "## rpc soak (mux byte-identity under sustained load, docs/DESIGN.md 'RPC substrate')"
  # the gate behind the SHARD_MUX/INGEST_MUX ON defaults: muxed
  # streams hammer identity-checked center reads with interleaved
  # large gossip frames on BOTH loops; the threaded loop doubles as
  # the dedicated-socket fallback proof (tools/bench_rpc.py --soak)
  SOAKDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu \
    python tools/bench_rpc.py --soak --dur 4 --payload-kb 64 \
      --out "$SOAKDIR/BENCH_rpc_soak.json"
  SOAK_RC=$?
  rm -rf "$SOAKDIR"
  echo "rpc soak rc=$SOAK_RC"
  echo "## ingest smoke (2-reader fleet over real sockets + kill-recovery, docs/DESIGN.md 'Distributed ingest')"
  # the distributed-ingest vertical end-to-end: two REAL reader
  # processes serving a real mmap shard tree to trainer worker
  # processes over pipelined wire-v2 raw batch frames.  The gate
  # asserts N=2 aggregate img/s >= 1.7x N=1 at identical total bytes,
  # BOTH readers served their ranges (per-reader ingest_pull spans +
  # served counters), and the kill leg recovered — reader 0 SIGKILLed
  # mid-epoch, the client fails over (stream completes), the fleet
  # watcher relaunches it, and the recovery counters land in the
  # monitor JSONL (tools/bench_ingest.py --smoke, exit 1 on any miss)
  INGESTDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu THEANOMPI_TPU_MONITOR="$INGESTDIR" \
    python tools/bench_ingest.py --smoke \
      --out "$INGESTDIR/BENCH_ingest_smoke.json"
  INGEST_RC=$?
  # same-host readers: batch frames must have ridden the shm lane
  if [ "$INGEST_RC" -eq 0 ]; then
    shm_lane_evidence "$INGESTDIR" "ingest batches" || INGEST_RC=1
  fi
  rm -rf "$INGESTDIR"
  echo "ingest smoke rc=$INGEST_RC"
  echo "## rpc smoke (concurrent-connection scaling on the selector event plane, docs/DESIGN.md 'RPC substrate')"
  # the event-plane vertical end-to-end: a REAL service process
  # (selector loop, pinned to one core) fronting hundreds of
  # concurrent authenticated connections, every one with a pull in
  # flight.  The gate asserts flat per-connection p99 across the
  # scaling points, the >=10x recovery of the committed PR-9
  # GIL-convoy baseline at the 12-client point, and the monitor JSONL
  # evidence (rpc/connections_total + service/requests_total from the
  # server process) — tools/bench_rpc.py --smoke, exit 1 on any miss.
  # 200-conn top point here (preflight's >=200-client bar); the
  # committed artifacts/BENCH_rpc_smoke.json carries the full
  # 1000-connection run.
  RPCDIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu \
    python tools/bench_rpc.py --smoke --conns 8,200 --dur 3 \
      --out "$RPCDIR/BENCH_rpc_smoke.json"
  RPC_RC=$?
  rm -rf "$RPCDIR"
  echo "rpc smoke rc=$RPC_RC"
  if [ "$TMLINT_RC" -ne 0 ] || [ "$GATE_RC" -ne 0 ] || [ "$PYTEST_RC" -ne 0 ] || [ "$ENTRY_RC" -ne 0 ] || [ "$MONITOR_RC" -ne 0 ] || [ "$COLLECTOR_RC" -ne 0 ] || [ "$RESILIENCE_RC" -ne 0 ] || [ "$SERVING_RC" -ne 0 ] || [ "$DECODE_RC" -ne 0 ] || [ "$FRONTDOOR_RC" -ne 0 ] || [ "$EXCHANGE_RC" -ne 0 ] || [ "$BUCKET_RC" -ne 0 ] || [ "$SHARD_RC" -ne 0 ] || [ "$HIER_RC" -ne 0 ] || [ "$SOAK_RC" -ne 0 ] || [ "$INGEST_RC" -ne 0 ] || [ "$RPC_RC" -ne 0 ]; then
    echo "PREFLIGHT: FAIL"
    [ "$TMLINT_RC" -ne 0 ] && echo "PREFLIGHT: tmlint --gate found NEW findings — fix or baseline with a reason (docs/ANALYSIS.md)"
    [ "$GATE_RC" -ne 0 ] && echo "PREFLIGHT: the -m gate subset itself failed — do NOT snapshot"
    exit 1
  fi
  echo "PREFLIGHT: GREEN"
} 2>&1 | tee "$LOG"
exit "${PIPESTATUS[0]}"
