#!/bin/bash
# Snapshot gate (round-4 verdict #6 / round-3 #4b): the FULL suite —
# slow tests included — plus the driver entry points must be green
# before any end-of-round snapshot.  Round 3 committed a slow e2e test
# that had never been run (it failed); nothing structural prevented a
# repeat until this script.
#
# Usage:  bash tools/preflight.sh [artifacts/preflight_rNN.log]
# Exit 0 = safe to snapshot.  Writes the full output to the log path
# (default artifacts/preflight.log) so the round log can cite it.
set -u
LOG="${1:-artifacts/preflight.log}"
cd "$(dirname "$0")/.."
{
  echo "# preflight $(date -u +%Y-%m-%dT%H:%M:%SZ) HEAD=$(git rev-parse --short HEAD)"
  echo "## pytest --runslow"
  python -m pytest tests/ --runslow -q
  PYTEST_RC=$?
  echo "pytest rc=$PYTEST_RC"
  echo "## __graft_entry__ (entry + dryrun_multichip on the virtual mesh)"
  # CPU-forced: a wedged axon tunnel must not hang the gate (the
  # driver compile-checks entry() on the real chip separately)
  THEANOMPI_TPU_ENTRY_CPU=1 python __graft_entry__.py
  ENTRY_RC=$?
  echo "graft_entry rc=$ENTRY_RC"
  if [ "$PYTEST_RC" -ne 0 ] || [ "$ENTRY_RC" -ne 0 ]; then
    echo "PREFLIGHT: FAIL"
    exit 1
  fi
  echo "PREFLIGHT: GREEN"
} 2>&1 | tee "$LOG"
exit "${PIPESTATUS[0]}"
