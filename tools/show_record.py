"""Print a training run's recorder curves (the reference shipped a
show_record plotting script over the Recorder's saved state —
SURVEY.md §2.10).

Reads the JSONL epoch records written by utils/recorder.py and prints
a per-epoch table plus ASCII sparklines for loss / val error /
images-per-sec.

Usage: python tools/show_record.py <snapshot_dir> [rank]
"""

from __future__ import annotations

import json
import os
import sys

BARS = "▁▂▃▄▅▆▇█"


def spark(values):
    vals = [v for v in values if v is not None]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    rng = (hi - lo) or 1.0
    return "".join(
        " " if v is None else BARS[int((v - lo) / rng * (len(BARS) - 1))]
        for v in values
    )


def main() -> int:
    if len(sys.argv) < 2:
        print(__doc__)
        return 2
    save_dir = sys.argv[1]
    rank = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    path = os.path.join(save_dir, f"record_rank{rank}.jsonl")
    if not os.path.exists(path):
        print(f"no record at {path}")
        return 1
    with open(path) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    if not recs:
        print("empty record")
        return 1

    print(f"{'epoch':>5} {'img/s':>9} {'train_loss':>11} {'val_loss':>9} "
          f"{'val_err':>8} {'calc':>7} {'comm':>7} {'wait':>7} {'load':>7}")
    for r in recs:
        t = r.get("time", {})
        fmt = lambda v, n=4: "-" if v is None else f"{v:.{n}f}"  # noqa: E731
        print(f"{r['epoch']:>5} {r['images_per_sec']:>9} "
              f"{fmt(r['train_loss']):>11} {fmt(r['val_loss']):>9} "
              f"{fmt(r['val_error']):>8} "
              + " ".join(f"{t.get(k, 0):>7.1f}"
                         for k in ("calc", "comm", "wait", "load")))
    print()
    print(f"train_loss  {spark([r['train_loss'] for r in recs])}")
    print(f"val_error   {spark([r['val_error'] for r in recs])}")
    print(f"images/sec  {spark([r['images_per_sec'] for r in recs])}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
