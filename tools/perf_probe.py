"""Single-chip perf probe for the ResNet-50 BSP step (VERDICT r1 #2).

Times the jitted train step under controlled variations (batch size,
compute dtype, stem layout, metrics on/off) with a value-readback fence
(the axon plugin's ``block_until_ready`` is unreliable — bench.py).
Optionally dumps a ``jax.profiler`` trace for offline analysis.

Usage:
    python tools/perf_probe.py --batch 128 256 --steps 30
    python tools/perf_probe.py --batch 256 --trace /tmp/trace
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _bootstrap  # noqa: F401  (makes JAX_PLATFORMS effective)
import jax
import jax.numpy as jnp
import numpy as np

from flop_constants import TRAIN_GFLOP_PER_IMAGE, V5E_PEAK_TFLOPS  # noqa: E402


def time_step(step, state, batch, rng, n_steps: int, warmup: int = 3):
    for _ in range(warmup):
        state, metrics = step(state, batch, rng)
    jnp.asarray(metrics["loss"]).block_until_ready()
    float(metrics["loss"])  # readback fence (axon block_until_ready lies)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, batch, rng)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert np.isfinite(loss)
    return dt / n_steps, state


def build(batch: int, dtype: str, variant: str,
          bn_act_impl: str = "xla", pool_impl: str = "xla"):
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.resnet50 import ResNet50
    from theanompi_tpu.data.imagenet import ImageNet_data
    from theanompi_tpu.parallel.mesh import data_mesh, shard_batch

    devices = jax.devices()
    mesh = data_mesh(len(devices), devices)
    global_batch = batch * len(devices)

    uint8_feed = variant == "uint8"

    class ProbeResNet50(ResNet50):
        def build_data(self):
            if uint8_feed:
                # the FLAGSHIP staging (bench.py device-step leg): raw
                # uint8 store images, crop/flip/normalize traced into
                # the step (ops/augment.py).  The f32 'base' variant
                # stages pre-normalized floats — its trace carries an
                # input f32->bf16 convert + 38 MB copy the flagship
                # step doesn't have (seen in the r3/r4 account), and
                # misses the device augment slice the flagship does.
                return ImageNet_data(crop=224, synthetic_n=global_batch,
                                     synthetic_pool=1,
                                     synthetic_store=256,
                                     augment_on_device=True)
            return ImageNet_data(crop=224, synthetic_n=global_batch,
                                 synthetic_pool=1, synthetic_store=32)

    cfg = ModelConfig(batch_size=batch, compute_dtype=dtype,
                      track_top5=False, print_freq=10**9,
                      bn_act_impl=bn_act_impl, pool_impl=pool_impl)
    model = ProbeResNet50(config=cfg, mesh=mesh, verbose=False)
    if variant not in ("base", "uint8"):
        raise ValueError(variant)
    model.compile_iter_fns("avg")

    if uint8_feed:
        x = np.random.default_rng(0).integers(
            0, 256, size=(global_batch, 256, 256, 3), dtype=np.uint8)
    else:
        x = np.random.default_rng(0).standard_normal(
            (global_batch, 224, 224, 3)).astype(np.float32)
    y = np.random.default_rng(1).integers(0, 1000, global_batch)
    staged = shard_batch((x, y), mesh)
    return model, staged, mesh, global_batch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, nargs="+", default=[128])
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--variant", default="base")
    ap.add_argument("--trace", default=None,
                    help="dump a jax.profiler trace to this dir")
    ap.add_argument("--xla-flags", default=None,
                    help="appended to XLA_FLAGS before first backend use "
                    "(round-5 queue: capture the profile under the "
                    "scoped-VMEM flag that wins the sweep)")
    ap.add_argument("--bn-act-impl", default="xla",
                    choices=("xla", "pallas"),
                    help="BN/activation epilogue kernel "
                    "(ops/fused_bn.py) — the A/B lever of the "
                    "xla_sweep fused-epilogue entries")
    ap.add_argument("--pool-impl", default="xla",
                    choices=("xla", "pallas"),
                    help="stem maxpool kernel (ops/maxpool_pallas.py)")
    args = ap.parse_args()
    if args.xla_flags:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " " + args.xla_flags)

    for b in args.batch:
        model, staged, mesh, global_batch = build(
            b, args.dtype, args.variant, args.bn_act_impl, args.pool_impl)
        rng = jax.random.key(0)
        step_s, state = time_step(model.train_step, model.state, staged, rng,
                                  args.steps)
        img_s = global_batch / step_s
        per_chip = img_s / len(jax.devices())
        tflops = per_chip * TRAIN_GFLOP_PER_IMAGE / 1000.0
        print(json.dumps({
            "batch_per_chip": b, "dtype": args.dtype, "variant": args.variant,
            "bn_act_impl": args.bn_act_impl, "pool_impl": args.pool_impl,
            "step_ms": round(step_s * 1e3, 2),
            "images_per_sec_per_chip": round(per_chip, 1),
            "tflops_per_chip": round(tflops, 1),
            "mfu_pct": round(100 * tflops / V5E_PEAK_TFLOPS, 1),
        }))
        if args.trace:
            with jax.profiler.trace(args.trace):
                for _ in range(5):
                    state, metrics = model.train_step(state, staged,
                                                      rng)
                float(metrics["loss"])
            print(f"trace written to {args.trace}")


if __name__ == "__main__":
    main()
