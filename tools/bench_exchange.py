"""Wire-protocol exchange benchmark — v1 pickle vs v2 framed, over
REAL sockets (ISSUE 5 measurement leg).

Drives a ResNet-50-sized (~25.5M param) parameter tree through the
param service's EASGD exchange in every (protocol, compression, dtype)
mode and reports, per mode:

* **bytes/exchange** — exact serialized request + reply bytes.  v2
  modes are measured by encoding the same frames the client sends
  (``wire.encode_frame`` is deterministic); v1 is measured by running
  the SAME reduction ``multiprocessing.connection.Connection.send``
  uses (``ForkingPickler.dumps``) on the request/reply tuples.
* **wall ms/exchange** — client-observed round-trip over a localhost
  TCP socket (serialize + socket + server elastic merge + reply).
  Localhost removes network bandwidth from the picture, so this is
  the floor the serialization layer itself sets; on a real DCN link
  the byte cut converts to time at the link's rate.

Emits ``artifacts/BENCH_wire_<tag>.json``.  ``--smoke`` is the
preflight gate: asserts v2-framed beats v1-pickle on bytes/exchange
and that the wire compression-ratio gauge landed in the monitor
JSONL (exit 1 otherwise).

Usage:
    python tools/bench_exchange.py                  # full, ~25M params
    python tools/bench_exchange.py --smoke          # preflight gate
    python tools/bench_exchange.py --params 1e6 --exchanges 5
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import _bootstrap  # noqa: F401,E402  (tools/ sibling; pins JAX_PLATFORMS)

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: (protocol, compression, dtype) — v1 has no negotiated options
MODES = (
    ("v1", "none", "f32"),
    ("v2", "none", "f32"),
    ("v2", "zlib", "f32"),
    ("v2", "none", "bf16"),
    ("v2", "zlib", "bf16"),
)


class FlagConflict(SystemExit):
    """Typed refusal for mutually exclusive bench legs (``--buckets``
    vs ``--shards``): the bucket leg drives the SPMD in-step exchange
    on a device mesh, the shard leg drives the wire exchange against
    real shard processes — silently ignoring one flag would report a
    number the caller did not ask for.  Exits 2 like an argparse
    usage error."""

    def __init__(self, msg: str):
        print(f"[bench_exchange] ERROR: {msg}", file=sys.stderr)
        super().__init__(2)


def resnet50_like_tree(target_params: int, seed: int = 0) -> dict:
    """A parameter tree with ResNet-50's leaf-size distribution
    (conv kernels from (7,7,3,64) up to (1,1,1024,2048), BN vectors,
    one big FC) scaled to ~``target_params`` total — the leaf-count /
    leaf-size mix is what exercises the per-buffer framing overhead
    realistically, not just one flat 100 MB blob."""
    rng = np.random.default_rng(seed)
    shapes: list[tuple[int, ...]] = [(7, 7, 3, 64)]
    stages = ((64, 64, 3), (256, 128, 4), (512, 256, 6), (1024, 512, 3))
    for c_in, c_mid, reps in stages:
        for r in range(reps):
            cin = c_in if r == 0 else c_mid * 4
            shapes += [(1, 1, cin, c_mid), (3, 3, c_mid, c_mid),
                       (1, 1, c_mid, c_mid * 4)]
            for width in (c_mid, c_mid, c_mid * 4):
                shapes += [(width,)] * 4      # BN scale/bias/mean/var
    shapes.append((2048, 1000))
    shapes.append((1000,))
    base_total = sum(int(np.prod(s)) for s in shapes)
    scale = max(1, round(target_params / base_total))
    tree = {}
    for i, s in enumerate(shapes):
        # scale by repeating leaves, preserving the size distribution
        for k in range(scale if len(s) > 1 else 1):
            tree[f"leaf_{i:03d}_{k}"] = rng.standard_normal(
                s).astype(np.float32) * 0.05
    return tree


def tree_params(tree: dict) -> int:
    return sum(int(v.size) for v in tree.values())


def tree_nbytes(tree: dict) -> int:
    return sum(int(v.nbytes) for v in tree.values())


def _pickle_len(obj) -> int:
    """Bytes ``Connection.send`` would write for ``obj`` (v1 wire)."""
    import io
    from multiprocessing.reduction import ForkingPickler

    buf = io.BytesIO()
    ForkingPickler(buf, -1).dump(obj)
    return buf.getbuffer().nbytes


def measure_mode(addr: str, protocol: str, compression: str, dtype: str,
                 tree: dict, n_exchanges: int) -> dict:
    from theanompi_tpu.parallel import wire
    from theanompi_tpu.parallel.service import RemoteEASGD

    opts = wire.WireOptions(compression=compression, dtype=dtype)
    sid = f"bench-{protocol}-{compression}-{dtype}"
    srv = RemoteEASGD.__new__(RemoteEASGD)
    # RemoteEASGD.__init__ ships the init tree too; time only the
    # steady-state exchanges, so construct with the real init path
    t0 = time.monotonic()
    RemoteEASGD.__init__(srv, addr, tree, alpha=0.5, session_id=sid)
    # force the requested protocol AFTER construction knobs: the env
    # route would leak across modes
    if protocol == "v1" and srv.wire_protocol != "v1":
        srv.close()
        from theanompi_tpu.parallel.service import RemoteEASGD as _R

        os.environ["THEANOMPI_TPU_WIRE_PROTOCOL"] = "v1"
        try:
            srv = _R(addr, tree, alpha=0.5, session_id=sid + "1")
        finally:
            os.environ.pop("THEANOMPI_TPU_WIRE_PROTOCOL", None)
    init_s = time.monotonic() - t0
    assert srv.wire_protocol == protocol, (srv.wire_protocol, protocol)

    # exact per-exchange wire bytes (request and reply carry the same
    # tree shape for the elastic exchange)
    request = ("easgd_exchange", sid, tree)
    reply = ("ok", tree)
    if protocol == "v2":
        head, bufs, st_req = wire.encode_frame(request, opts)
        _, _, st_rep = wire.encode_frame(reply, opts)
        bytes_sent, bytes_recv = st_req.post_bytes, st_rep.post_bytes
        pre_bytes = st_req.pre_bytes
    else:
        bytes_sent = _pickle_len(request)
        bytes_recv = _pickle_len(reply)
        pre_bytes = bytes_sent

    walls = []
    for i in range(n_exchanges):
        t0 = time.monotonic()
        out = srv.exchange(tree)
        walls.append((time.monotonic() - t0) * 1e3)
    # sanity: the arithmetic survived the transport
    k = next(iter(tree))
    assert np.isfinite(out[k]).all()
    srv.close()
    total = bytes_sent + bytes_recv
    return {
        "protocol": protocol, "compression": compression, "dtype": dtype,
        "bytes_sent_per_exchange": bytes_sent,
        "bytes_recv_per_exchange": bytes_recv,
        "bytes_per_exchange": total,
        "pre_bytes": pre_bytes,
        "wire_ratio": round(total / (2 * pre_bytes), 4),
        "n_exchanges": n_exchanges,
        "wall_ms_mean": round(float(np.mean(walls)), 2),
        "wall_ms_min": round(float(np.min(walls)), 2),
        "init_s": round(init_s, 3),
    }


def run_sharded(args) -> int:
    """``--shards K`` mode (ISSUE 8): drive the same parameter tree
    against K REAL shard processes via the shard router and compare
    per-shard and aggregate bytes/wall against K=1.  The aggregate
    exchange scatters K concurrent sub-exchanges (each shard process
    serializes + merges its leaf range in parallel), so aggregate wall
    should beat the single-center round trip on a multi-core box.

    ``--smoke`` additionally kills shard 0 mid-run, waits for the
    supervised relaunch, and asserts (a) both shards served traffic,
    (b) the kill recovered (exchange succeeds, reconnect + restart
    events land in the monitor JSONL) — the preflight 2-shard gate."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    os.environ.setdefault("THEANOMPI_TPU_SERVICE_KEY", "bench-exchange")
    os.environ.setdefault(
        "THEANOMPI_TPU_MONITOR",
        os.path.join(REPO, "artifacts", "bench_exchange_monitor"))

    from theanompi_tpu import monitor
    from theanompi_tpu.parallel import wire
    from theanompi_tpu.parallel.shards import (
        ShardProcessGroup,
        ShardedEASGD,
    )

    k = int(args.shards)
    n_exchanges = max(3, args.exchanges)
    tree = resnet50_like_tree(int(args.params))
    n_params = tree_params(tree)
    print(f"[bench_exchange] shard mode: {n_params/1e6:.1f}M params, "
          f"{len(tree)} leaves, {tree_nbytes(tree)/1e6:.1f} MB f32, "
          f"K in (1, {k})", flush=True)
    opts = wire.WireOptions.from_env()

    modes = []
    kill_recovered = None
    with monitor.session():
        for n_shards in ([1, k] if k > 1 else [1]):
            group = ShardProcessGroup(n_shards, max_restarts=2)
            try:
                sid = f"bench-shards-{n_shards}"
                srv = ShardedEASGD(group.addresses, tree, alpha=0.5,
                                   session_id=sid)
                # exact per-shard wire bytes: encode the same frames
                # the router's sub-exchanges send/receive
                per_shard = []
                flat, _ = jax.tree.flatten(tree)
                for i, (lo, hi) in enumerate(srv._plan.ranges):
                    sub = flat[lo:hi]
                    _, _, st_req = wire.encode_frame(
                        ("shard_exchange", sid, sub, "cid", 1), opts)
                    _, _, st_rep = wire.encode_frame(("ok", sub), opts)
                    per_shard.append({
                        "shard": i, "n_leaves": hi - lo,
                        "bytes_sent_per_exchange": st_req.post_bytes,
                        "bytes_recv_per_exchange": st_rep.post_bytes,
                    })
                # probe rounds: each shard timed alone (sequential) so
                # the wall is attributable to THAT shard; repeated so
                # the per-shard tail (p50/p99) is reported alongside
                # the aggregate concurrent wall — a single probe hid a
                # slow shard entirely (ISSUE 13 satellite fix)
                probe_rounds = max(5, n_exchanges)
                probes = [[] for _ in srv._plan.ranges]
                # one untimed warmup round first: the session's first
                # tagged exchange pays one-off jit/session costs that
                # would otherwise masquerade as the p99 tail
                for r in range(probe_rounds + 1):
                    seq = srv._next_seq()
                    for i, (lo, hi) in enumerate(srv._plan.ranges):
                        t0 = time.monotonic()
                        srv._shard_clients[i].exchange_tagged(
                            flat[lo:hi], srv._client_id, seq)
                        if r > 0:
                            probes[i].append(
                                (time.monotonic() - t0) * 1e3)
                for i, ws in enumerate(probes):
                    per_shard[i]["probe_wall_ms"] = round(ws[0], 2)
                    per_shard[i]["probe_wall_p50_ms"] = round(
                        float(np.percentile(ws, 50)), 2)
                    per_shard[i]["probe_wall_p99_ms"] = round(
                        float(np.percentile(ws, 99)), 2)
                    per_shard[i]["probe_rounds"] = probe_rounds
                walls = []
                for _ in range(n_exchanges):
                    t0 = time.monotonic()
                    out = srv.exchange(tree)
                    walls.append((time.monotonic() - t0) * 1e3)
                assert np.isfinite(out[next(iter(tree))]).all()
                if args.smoke and n_shards > 1:
                    # fault leg: hard-kill shard 0, let the group
                    # relaunch it, prove the router recovers (the
                    # per-shard rejoin re-seeds only shard 0's range)
                    group.kill_shard(0)
                    group.wait_restarted(0)
                    out = srv.exchange(tree)
                    kill_recovered = bool(
                        np.isfinite(out[next(iter(tree))]).all()
                        and group.restart_counts().get(0) == 1)
                    print(f"[bench_exchange] shard-0 kill recovered: "
                          f"{kill_recovered}", flush=True)
                srv.close()
                modes.append({
                    "shards": n_shards,
                    "n_exchanges": n_exchanges,
                    "wall_ms_mean": round(float(np.mean(walls)), 2),
                    "wall_ms_min": round(float(np.min(walls)), 2),
                    "bytes_per_exchange": sum(
                        p["bytes_sent_per_exchange"]
                        + p["bytes_recv_per_exchange"]
                        for p in per_shard),
                    "per_shard": per_shard,
                })
                print(f"[bench_exchange] K={n_shards}: "
                      f"{modes[-1]['wall_ms_mean']:.0f} ms mean, "
                      f"{modes[-1]['bytes_per_exchange']/1e6:.1f} "
                      "MB/exchange", flush=True)
            finally:
                group.stop()
        snapshot_path = monitor.flush()

    k1 = next(m for m in modes if m["shards"] == 1)
    kk = next(m for m in modes if m["shards"] == k)
    improvement = 1.0 - kk["wall_ms_mean"] / k1["wall_ms_mean"]
    out_doc = {
        "bench": "shard_exchange",
        "backend": "cpu",
        "n_params": n_params,
        "n_leaves": len(tree),
        "tree_mb_f32": round(tree_nbytes(tree) / 1e6, 2),
        "wire": {"compression": opts.compression, "dtype": opts.dtype},
        "modes": modes,
        "aggregate_wall_improvement_vs_k1": round(improvement, 4),
        "kill_recovered": kill_recovered,
    }
    tag = args.tag or ("shard_smoke" if args.smoke else f"shard_k{k}")
    path = args.out or os.path.join(REPO, "artifacts",
                                    f"BENCH_{tag}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out_doc, f, indent=1)
    print(f"[bench_exchange] wrote {path} (K={k} aggregate wall "
          f"{improvement:+.1%} vs K=1)", flush=True)

    if not args.smoke:
        return 0
    ok = True
    if k < 2:
        print("[bench_exchange] FAIL: shard smoke needs --shards >= 2",
              file=sys.stderr)
        ok = False
    if improvement <= 0:
        print(f"[bench_exchange] FAIL: K={k} aggregate wall "
              f"({kk['wall_ms_mean']} ms) does not improve on K=1 "
              f"({k1['wall_ms_mean']} ms)", file=sys.stderr)
        ok = False
    if kill_recovered is not True:
        print("[bench_exchange] FAIL: shard-0 kill did not recover",
              file=sys.stderr)
        ok = False
    # monitor JSONL: per-shard traffic (shard_exchange spans for every
    # shard) + the recovery events (client reconnect, shard relaunch)
    served, names = set(), set()
    shm_oob = 0.0
    if snapshot_path and os.path.exists(snapshot_path):
        with open(snapshot_path) as f:
            for line in f:
                rec = json.loads(line)
                names.add(rec.get("name"))
                if rec.get("name") == "shm/oob_bytes_total":
                    shm_oob = max(shm_oob,
                                  float(rec.get("value") or 0.0))
                if (rec.get("name") == "span_ms"
                        and rec.get("labels", {}).get("name")
                        == "shard_exchange" and rec.get("count", 0) > 0):
                    served.add(rec["labels"].get("worker"))
    missing_shards = {str(i) for i in range(k)} - served
    if missing_shards:
        print(f"[bench_exchange] FAIL: no shard_exchange spans for "
              f"shard(s) {sorted(missing_shards)} in the monitor JSONL "
              f"({snapshot_path})", file=sys.stderr)
        ok = False
    for needed in ("service/client_reconnects_total",
                   "service/shard_restarts_total"):
        if needed not in names:
            print(f"[bench_exchange] FAIL: {needed} missing from the "
                  f"monitor JSONL ({snapshot_path})", file=sys.stderr)
            ok = False
    # shm-lane evidence (ISSUE 20): a same-host shard fleet must have
    # granted the lane and shipped the big leaves out-of-band — the
    # client side of both counters lands in THIS process's snapshot
    from theanompi_tpu.parallel import shm

    if shm.enabled() and shm.available():
        if "shm/grants_total" not in names or shm_oob <= 0:
            print(f"[bench_exchange] FAIL: no shm-lane evidence in "
                  f"the monitor JSONL ({snapshot_path}): grants "
                  f"{'present' if 'shm/grants_total' in names else 'missing'}, "
                  f"oob_bytes {shm_oob:.0f} — same-host shards should "
                  "have granted the lane", file=sys.stderr)
            ok = False
    print(f"[bench_exchange] shard smoke {'PASS' if ok else 'FAIL'}",
          flush=True)
    return 0 if ok else 1


def run_shm_compare(args) -> int:
    """``--shm-compare`` (ISSUE 20): the shared-memory-lane
    comparison across the three same-host planes, one committed
    artifact (``artifacts/BENCH_shm_smoke.json``).

    Exchange plane: the full parameter tree against ONE real shard
    process — in-band wire v2 vs the negotiated shm lane, identical
    exchange schedule, every round's merged tree sha256-checked
    across legs, each leg against a FRESH server process.  The shm
    leg ends with the lane FORCE-DISABLED mid-run on the live
    client (the refusal recovery path: drop the lane, reconnect
    without an offer): the tail exchanges must stay byte-identical
    with ZERO out-of-band growth — the silent-fallback proof.  A
    separate kill leg SIGKILLs the server between an exchange and
    its piggybacked ack (so its reply segments are still leased),
    then asserts the dead peer's segments sweep to zero.

    Ingest and serving planes ride the sibling tools' legs
    (``bench_ingest.shm_compare_leg`` /
    ``bench_serving.shm_compare_leg``) so each plane's measurement
    lives next to its own bench.

    ``--smoke`` enforces the acceptance bars: >= 25% exchange wall
    cut, >= 1.3x ingest img/s, byte identity on every plane, lane
    evidence in the monitor registry, zero leaked segments after
    every leg including the kill leg."""
    import hashlib

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    os.environ.setdefault("THEANOMPI_TPU_SERVICE_KEY", "bench-exchange")
    os.environ.setdefault(
        "THEANOMPI_TPU_MONITOR",
        os.path.join(REPO, "artifacts", "bench_exchange_monitor"))

    from theanompi_tpu import monitor
    from theanompi_tpu.parallel import shm, wire
    from theanompi_tpu.parallel.shards import (
        ShardProcessGroup,
        ShardedEASGD,
    )

    if not (shm.enabled() and shm.available()):
        print("[bench_exchange] FAIL: the shm lane is disabled or "
              "/dev/shm is unavailable on this host", file=sys.stderr)
        return 1

    tree = resnet50_like_tree(int(args.params))
    n_params = tree_params(tree)
    n_exchanges = max(3, args.exchanges)
    tail = 2  # post-force-disable exchanges (the fallback proof)
    print(f"[bench_exchange] shm-compare: {n_params/1e6:.1f}M params, "
          f"{len(tree)} leaves, {tree_nbytes(tree)/1e6:.1f} MB f32, "
          f"{n_exchanges} timed + {tail} fallback exchanges/leg",
          flush=True)

    # exact in-band wire bytes (the copied-bytes ledger baseline):
    # the same frames the K=1 router sends/receives, no lane attached
    opts = wire.WireOptions.from_env()
    flat, _ = jax.tree.flatten(tree)
    _, _, st_req = wire.encode_frame(
        ("shard_exchange", "bench-shm", flat, "cid", 1), opts)
    _, _, st_rep = wire.encode_frame(("ok", flat), opts)
    wire_bytes = st_req.post_bytes + st_rep.post_bytes

    keys = sorted(tree)

    def tree_digest(t: dict) -> str:
        h = hashlib.sha256()
        for k in keys:
            h.update(np.asarray(t[k]).tobytes())
        return h.hexdigest()

    # lazy registry lookup: monitor.session() swaps in a FRESH
    # registry on activation, so a handle captured here would read
    # the stale pre-session one (and count nothing)
    val = lambda name, **lb: (
        monitor.registry().value(name, **lb) or 0.0)
    oob_total = lambda: (val("shm/oob_bytes_total", dir="send")
                         + val("shm/oob_bytes_total", dir="recv"))
    pre_segments = set(shm.segment_names())
    prior_lane = os.environ.get("THEANOMPI_TPU_WIRE_SHM")

    def exchange_leg(lane: str) -> dict:
        """One fresh-server leg: warm + timed + tail exchanges, every
        merged tree digested.  ``lane`` toggles the hello offer for
        BOTH sides (the shard subprocess inherits the environment)."""
        os.environ["THEANOMPI_TPU_WIRE_SHM"] = lane
        grants0 = val("shm/grants_total", role="client")
        oob0 = oob_total()
        digests: list[str] = []
        walls: list[float] = []
        group = ShardProcessGroup(1, max_restarts=1)
        try:
            srv = ShardedEASGD(group.addresses, tree, alpha=0.5,
                               session_id=f"bench-shm-{lane}")
            try:
                out = srv.exchange(tree)  # warm: jit + session setup
                digests.append(tree_digest(out))
                for _ in range(n_exchanges):
                    t0 = time.monotonic()
                    out = srv.exchange(tree)
                    walls.append((time.monotonic() - t0) * 1e3)
                    digests.append(tree_digest(out))
                oob_tail0 = oob_total()
                if lane == "1":
                    # force-disable mid-run on the LIVE client: the
                    # same degrade path a typed refusal takes — drop
                    # the lane, reconnect without an offer
                    for c in srv._shard_clients:
                        c._disable_shm()
                        if getattr(c, "_transport", None) is None:
                            try:
                                c._conn.close()
                            except OSError:
                                pass
                for _ in range(tail):
                    out = srv.exchange(tree)
                    digests.append(tree_digest(out))
                oob_tail_growth = oob_total() - oob_tail0
            finally:
                srv.close()
        finally:
            group.stop()
        oob = oob_total() - oob0
        leg = {
            "wall_ms_mean": round(float(np.mean(walls)), 2),
            "wall_ms_min": round(float(np.min(walls)), 2),
            "n_exchanges": n_exchanges,
            "digests": digests,
            "shm_grants": int(val("shm/grants_total", role="client")
                              - grants0),
            "oob_bytes": int(oob),
            "oob_bytes_per_exchange": int(oob / (n_exchanges + 1)),
            "oob_tail_growth": int(oob_tail_growth),
        }
        print(f"[bench_exchange] shm-compare "
              f"{'shm' if lane == '1' else 'in_band'}: "
              f"{leg['wall_ms_mean']:.0f} ms/exchange mean, "
              f"{leg['oob_bytes']/1e6:.1f} MB out-of-band", flush=True)
        return leg

    def kill_leg() -> dict:
        """SIGKILL the server while its reply segments are still
        leased (the ack rides the client's NEXT frame, which never
        comes), then prove the dead peer's segments sweep to zero."""
        os.environ["THEANOMPI_TPU_WIRE_SHM"] = "1"
        group = ShardProcessGroup(1, max_restarts=0)
        try:
            srv = ShardedEASGD(group.addresses, tree, alpha=0.5,
                               session_id="bench-shm-kill")
            try:
                srv.exchange(tree)
                srv.exchange(tree)
                orphans_before = len(
                    [n for n in shm.segment_names()
                     if n not in pre_segments])
                group.kill_shard(0)
            finally:
                try:
                    srv.close()
                except Exception:
                    pass
        finally:
            group.stop()
        shm.release_all()
        swept = shm.sweep_orphans()
        leaked = [n for n in shm.segment_names()
                  if n not in pre_segments]
        out = {"leased_at_kill": orphans_before,
               "swept": int(swept or 0),
               "leaked_after_sweep": len(leaked)}
        print(f"[bench_exchange] shm-compare kill leg: {out}",
              flush=True)
        return out

    planes: dict[str, dict] = {}
    with monitor.session():
        try:
            in_band = exchange_leg("0")
            lane = exchange_leg("1")
            kill = kill_leg()
        finally:
            if prior_lane is None:
                os.environ.pop("THEANOMPI_TPU_WIRE_SHM", None)
            else:
                os.environ["THEANOMPI_TPU_WIRE_SHM"] = prior_lane
        wall_cut = 1.0 - lane["wall_ms_mean"] / in_band["wall_ms_mean"]
        planes["exchange"] = {
            "plane": "exchange",
            "n_params": n_params,
            "wire_bytes_per_exchange_in_band": wire_bytes,
            "legs": {"in_band": in_band, "shm": lane},
            "wall_cut_shm_vs_in_band": round(wall_cut, 4),
            "byte_identical": in_band["digests"] == lane["digests"],
            # payload bytes that left the socket path entirely per
            # exchange (receiver maps instead of copying off the wire)
            "socket_bytes_saved_per_exchange":
                lane["oob_bytes_per_exchange"],
            "kill_leg": kill,
        }
        print(f"[bench_exchange] exchange plane: shm cuts "
              f"{wall_cut:.1%} of the in-band wall", flush=True)

        # sibling planes: same artifact, each leg owned by its bench
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import bench_ingest
        import bench_serving

        planes["ingest"] = bench_ingest.shm_compare_leg(
            samples=4096 if args.smoke else 8192)
        print(f"[bench_exchange] ingest plane: shm "
              f"{planes['ingest']['img_s_ratio_shm_over_in_band']:.2f}"
              "x in-band img/s", flush=True)
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            planes["serving"] = bench_serving.shm_compare_leg(td)
        print(f"[bench_exchange] serving plane: shm wall delta "
              f"{planes['serving']['wall_delta_pct']:+.1f}%",
              flush=True)

    leaked_final = [n for n in shm.segment_names()
                    if n not in pre_segments]
    # digests are leg-internal evidence; keep the artifact readable
    for leg in planes["exchange"]["legs"].values():
        leg.pop("digests", None)
    out_doc = {
        "bench": "shm_lane",
        "backend": "cpu",
        "n_params": n_params,
        "n_leaves": len(tree),
        "tree_mb_f32": round(tree_nbytes(tree) / 1e6, 2),
        "planes": planes,
        "leaked_segments_final": len(leaked_final),
    }
    tag = args.tag or "shm_smoke"
    path = args.out or os.path.join(REPO, "artifacts",
                                    f"BENCH_{tag}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out_doc, f, indent=1)
    print(f"[bench_exchange] wrote {path}", flush=True)

    if not args.smoke:
        return 0
    ok = True
    ex = planes["exchange"]
    if not ex["byte_identical"]:
        print("[bench_exchange] FAIL: shm exchange leg diverged from "
              "the in-band leg (byte identity)", file=sys.stderr)
        ok = False
    if ex["wall_cut_shm_vs_in_band"] < 0.25:
        print(f"[bench_exchange] FAIL: shm wall cut "
              f"{ex['wall_cut_shm_vs_in_band']:.1%} < 25%",
              file=sys.stderr)
        ok = False
    legs = ex["legs"]
    if legs["shm"]["shm_grants"] < 1 or legs["shm"]["oob_bytes"] <= 0:
        print("[bench_exchange] FAIL: shm leg shows no lane traffic "
              f"({legs['shm']})", file=sys.stderr)
        ok = False
    if legs["in_band"]["oob_bytes"] != 0 \
            or legs["in_band"]["shm_grants"] != 0:
        print("[bench_exchange] FAIL: in-band leg negotiated the lane "
              f"({legs['in_band']})", file=sys.stderr)
        ok = False
    if legs["shm"]["oob_tail_growth"] != 0:
        print("[bench_exchange] FAIL: out-of-band bytes grew after "
              "the mid-run force-disable — the fallback is not "
              "in-band", file=sys.stderr)
        ok = False
    if ex["kill_leg"]["leased_at_kill"] < 1:
        print("[bench_exchange] FAIL: kill leg found no leased "
              "segment at SIGKILL time — the leg proved nothing",
              file=sys.stderr)
        ok = False
    if ex["kill_leg"]["leaked_after_sweep"] != 0:
        print(f"[bench_exchange] FAIL: {ex['kill_leg']} — dead peer's "
              "segments survived the sweep", file=sys.stderr)
        ok = False
    ing = planes["ingest"]
    if not ing["byte_identical"]:
        print("[bench_exchange] FAIL: ingest shm leg delivered "
              "different bytes", file=sys.stderr)
        ok = False
    if ing["img_s_ratio_shm_over_in_band"] < 1.3:
        print(f"[bench_exchange] FAIL: ingest shm img/s "
              f"{ing['img_s_ratio_shm_over_in_band']:.2f}x < 1.3x",
              file=sys.stderr)
        ok = False
    srv_plane = planes["serving"]
    if not srv_plane["byte_identical"]:
        print("[bench_exchange] FAIL: serving shm leg delivered "
              "different page bytes", file=sys.stderr)
        ok = False
    if srv_plane["legs"]["shm"]["oob_bytes_recv"] <= 0:
        print("[bench_exchange] FAIL: serving shm leg shows no lane "
              "traffic", file=sys.stderr)
        ok = False
    if leaked_final:
        print(f"[bench_exchange] FAIL: {len(leaked_final)} shm "
              f"segment(s) leaked after all legs ({leaked_final})",
              file=sys.stderr)
        ok = False
    print(f"[bench_exchange] shm-compare smoke "
          f"{'PASS' if ok else 'FAIL'}", flush=True)
    return 0 if ok else 1


def lattice_tree(target_params: int, seed: int = 0,
                 grid_bits: int = 10) -> dict:
    """``resnet50_like_tree`` snapped to the exact-arithmetic f32
    lattice (integer multiples of 2**-grid_bits, magnitudes << 2**10):
    every sum/mean/elastic-pull the hierarchical plane computes stays
    exactly representable, so the trajectory pins compare BITWISE
    instead of hiding behind a tolerance — f32 associativity cannot
    blur what the aggregation math actually did.

    ``+ 0.0`` flushes the ``-0.0`` entries ``np.round`` mints for
    small negatives: IEEE cancellation yields ``+0.0`` while a
    summed-then-applied ``-0.0`` delta preserves the sign, so signed
    zeros would flip BYTES between the direct and aggregated paths at
    exactly-zero positions — numerically equal, bitwise noise."""
    grid = float(1 << grid_bits)
    return {k: (np.round(v * grid) / grid + 0.0).astype(np.float32)
            for k, v in resnet50_like_tree(target_params, seed).items()}


def run_hierarchy(args) -> int:
    """``--local-workers N`` mode (ISSUE 14): hierarchical intra-host
    aggregation (``parallel/aggregate.py``) against K REAL shard
    processes, vs N direct per-worker exchanges — per-period wire-byte
    accounting plus trajectory pins:

    * **EASGD** — the aggregated center must equal the closed-form
      composition of N same-version exchanges (exact on the
      lattice-valued tree; f32-tolerance in general —
      docs/DESIGN.md "Hierarchical exchange").  The direct-vs-
      aggregated center delta is reported too: a direct chain applies
      the exchanges sequentially, an O(alpha^2) order effect the doc
      quantifies.
    * **ASGD** — the aggregated delta-sum must match N direct
      same-version pushes BYTE-identically (plain-SGD pushes commute
      exactly on the lattice), pinning that hierarchy changes where
      bytes travel, never what the center computes.

    ``--smoke`` is the preflight gate: asserts the N=4 wire-byte
    reduction (>= 3.9x of the direct baseline — the aggregate frame's
    multiplier arg costs a few skeleton bytes of the exact 4x), both
    pins, and the fan-in gauge + ``local_aggregate`` spans in the
    monitor JSONL; exit 1 otherwise."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    os.environ.setdefault("THEANOMPI_TPU_SERVICE_KEY", "bench-exchange")
    os.environ.setdefault(
        "THEANOMPI_TPU_MONITOR",
        os.path.join(REPO, "artifacts", "bench_exchange_monitor"))

    from theanompi_tpu import monitor
    from theanompi_tpu.parallel import wire
    from theanompi_tpu.parallel.aggregate import (
        AggregatedExchange,
        LocalAggregator,
    )
    from theanompi_tpu.parallel.shards import (
        ShardProcessGroup,
        ShardedASGD,
        ShardedEASGD,
    )

    n_workers = int(args.local_workers)
    k = int(args.shards or 1)
    # --smoke is a GATE, not the artifact: only the asserted (K, N)
    # combo runs, at 2 periods (the pins need >= 2 to compose) —
    # the full K x N matrix with wall statistics is the committed-
    # artifact (non-smoke) run, like every other bench mode's split
    periods = 2 if args.smoke else max(3, args.exchanges)
    alpha = 0.25  # N*alpha <= 1 at N=4 (docs/DESIGN.md stability note)
    base = lattice_tree(int(args.params))
    n_params = tree_params(base)
    rng = np.random.default_rng(3)
    drifts = [
        {kk: (rng.integers(-64, 65, v.shape) * 2.0**-10)
         .astype(np.float32) for kk, v in base.items()}
        for _ in range(n_workers)]
    print(f"[bench_exchange] hierarchy mode: {n_params/1e6:.1f}M "
          f"params, {len(base)} leaves, "
          f"{tree_nbytes(base)/1e6:.1f} MB f32, N in (1, {n_workers}), "
          f"K in (1, {k})", flush=True)
    opts = wire.WireOptions.from_env()

    def frame_bytes(op_tuple) -> int:
        _, _, st = wire.encode_frame(op_tuple, opts)
        return st.post_bytes

    def shard_subs(client, tree):
        flat, _ = jax.tree.flatten(tree)
        flat = [np.asarray(a) for a in flat]
        return [flat[lo:hi] for lo, hi in client._plan.ranges]

    def worker_start(i):
        return {kk: base[kk] + drifts[i][kk] for kk in base}

    def run_leg(n_shards, n_local, hierarchical):
        """One (K, N, mode) leg on a fresh fleet; returns the measured
        row + the final center (for the trajectory pins)."""
        group = ShardProcessGroup(n_shards, max_restarts=1)
        sid = (f"hier-{n_shards}-{n_local}"
               if hierarchical else f"direct-{n_shards}-{n_local}")
        srv = ShardedEASGD(group.addresses, base, alpha=alpha,
                           session_id=sid)
        try:
            workers = [worker_start(i) for i in range(n_local)]
            walls = []
            if hierarchical:
                agg = LocalAggregator("easgd", srv, alpha=alpha)
                ports = [AggregatedExchange(
                    agg, i, lambda: ShardedEASGD(
                        group.addresses, None, alpha=alpha,
                        session_id=sid)) for i in range(n_local)]
                for _ in range(periods):
                    outs = [None] * n_local
                    ths = [threading.Thread(
                        target=lambda i=i: outs.__setitem__(
                            i, ports[i].exchange(workers[i])))
                        for i in range(n_local)]
                    t0 = time.monotonic()
                    for t in ths:
                        t.start()
                    for t in ths:
                        t.join()
                    walls.append((time.monotonic() - t0) * 1e3)
                    workers = [
                        {kk: outs[i][kk] + drifts[i][kk] for kk in base}
                        for i in range(n_local)]
                for p in ports:
                    p.close()
                # wire bytes/period: ONE tagged aggregate sub-exchange
                # per shard (mean tree out, pre-update center back)
                per_period = sum(
                    frame_bytes(("shard_exchange", sid, sub, "cid", 1,
                                 n_local)) + frame_bytes(("ok", sub))
                    for sub in shard_subs(srv, base))
            else:
                clients = [srv] + [
                    ShardedEASGD(group.addresses, None, alpha=alpha,
                                 session_id=sid)
                    for _ in range(n_local - 1)]
                for _ in range(periods):
                    outs = [None] * n_local
                    ths = [threading.Thread(
                        target=lambda i=i: outs.__setitem__(
                            i, clients[i].exchange(workers[i])))
                        for i in range(n_local)]
                    t0 = time.monotonic()
                    for t in ths:
                        t.start()
                    for t in ths:
                        t.join()
                    walls.append((time.monotonic() - t0) * 1e3)
                    workers = [
                        {kk: np.asarray(outs[i][kk]) + drifts[i][kk]
                         for kk in base} for i in range(n_local)]
                for c in clients[1:]:
                    c.close()
                # wire bytes/period: N full scatters (worker tree out,
                # new worker tree back, per shard, per worker)
                per_period = n_local * sum(
                    frame_bytes(("shard_exchange", sid, sub, "cid", 1))
                    + frame_bytes(("ok", sub))
                    for sub in shard_subs(srv, base))
            center = srv.get_center()
            return {
                "wall_ms_mean": round(float(np.mean(walls)), 2),
                "wall_ms_min": round(float(np.min(walls)), 2),
                "wire_bytes_per_period": per_period,
            }, center
        finally:
            srv.close()
            group.stop()

    def easgd_closed_form():
        """N same-version exchanges per period, composed on host —
        the reference the aggregated leg is pinned against."""
        c = {kk: v.copy() for kk, v in base.items()}
        workers = [worker_start(i) for i in range(n_workers)]
        a = np.float32(alpha)
        for _ in range(periods):
            new_c = {kk: c[kk] + a * sum(w[kk] - c[kk] for w in workers)
                     for kk in base}
            workers = [
                {kk: (w[kk] - a * (w[kk] - c[kk])) + drifts[i][kk]
                 for kk in base} for i, w in enumerate(workers)]
            c = new_c
        return c

    def max_abs_diff(t1, t2) -> float:
        return max(float(np.max(np.abs(np.asarray(t1[kk])
                                       - np.asarray(t2[kk]))))
                   for kk in base)

    def asgd_pin(n_shards) -> bool:
        """Direct N same-version plain-SGD pushes vs ONE aggregated
        delta-sum push, on the lattice: byte-identical centers."""
        small = lattice_tree(int(min(args.params, 2e5)), seed=5)
        grads = [
            {kk: (np.random.default_rng(50 + i)
                  .integers(-8, 9, v.shape) * 2.0**-10)
             .astype(np.float32) for kk, v in small.items()}
            for i in range(n_workers)]
        opt_cfg = dict(learning_rate=0.125, optimizer="sgd")
        finals = []
        for mode in ("direct", "hier"):
            group = ShardProcessGroup(n_shards, max_restarts=1)
            sid = f"asgd-pin-{mode}-{n_shards}"
            srv = ShardedASGD(group.addresses, small, opt_cfg,
                              session_id=sid)
            try:
                for _ in range(periods):
                    if mode == "direct":
                        for g in grads:
                            srv.push_pull(g)
                    else:
                        gsum = {kk: np.sum([g[kk] for g in grads],
                                           axis=0, dtype=np.float32)
                                for kk in small}
                        srv.push_pull_n(gsum, n_workers)
                # the pin compares MATH: an at-least-once transport
                # duplicate (reconnect + re-send under load) would
                # legitimately shift the center — detect and report it
                # as transport noise, not a math miss
                n_updates = srv.n_updates
                finals.append((srv.get_center(), n_updates))
            finally:
                srv.close()
                group.stop()
        (c_direct, n_direct), (c_hier, n_hier) = finals
        expect = periods * n_workers
        if n_direct != expect or n_hier != expect:
            print(f"[bench_exchange] asgd pin saw a transport re-send "
                  f"(updates direct={n_direct} hier={n_hier}, expected "
                  f"{expect}) — at-least-once duplicate, not a math "
                  "miss; pin inconclusive this run", file=sys.stderr)
            return None
        bad = [kk for kk in small
               if np.asarray(c_direct[kk]).tobytes()
               != np.asarray(c_hier[kk]).tobytes()]
        if bad:
            worst = max(float(np.max(np.abs(np.asarray(c_direct[kk])
                                            - np.asarray(c_hier[kk]))))
                        for kk in bad)
            print(f"[bench_exchange] asgd pin mismatch on "
                  f"{len(bad)}/{len(small)} leaves "
                  f"(max abs diff {worst})", file=sys.stderr)
        return not bad

    combos = ([(k, n_workers)] if args.smoke else
              [(s, n) for s in sorted({1, k})
               for n in sorted({1, n_workers})])
    modes = []
    with monitor.session():
        for n_shards, n_local in combos:
            direct, d_center = run_leg(n_shards, n_local, False)
            hier, h_center = run_leg(n_shards, n_local, True)
            row = {
                "shards": n_shards, "local_workers": n_local,
                "periods": periods,
                "direct": direct, "hierarchical": hier,
                "wire_byte_reduction_x": round(
                    direct["wire_bytes_per_period"]
                    / hier["wire_bytes_per_period"], 4),
                "wall_delta_vs_direct": round(
                    1.0 - hier["wall_ms_mean"]
                    / direct["wall_ms_mean"], 4),
                "easgd_direct_vs_hier_center_max_abs_diff":
                    max_abs_diff(d_center, h_center),
            }
            if n_local == n_workers:
                row["easgd_closed_form_max_abs_diff"] = \
                    max_abs_diff(h_center, easgd_closed_form())
            modes.append(row)
            print(f"[bench_exchange] K={n_shards} N={n_local}: "
                  f"{row['wire_byte_reduction_x']}x fewer wire "
                  f"bytes/period "
                  f"({direct['wire_bytes_per_period']/1e6:.1f} -> "
                  f"{hier['wire_bytes_per_period']/1e6:.1f} MB), "
                  f"wall {direct['wall_ms_mean']:.0f} -> "
                  f"{hier['wall_ms_mean']:.0f} ms", flush=True)
        asgd_identical = asgd_pin(k)
        if asgd_identical is None:  # transport re-send: one more try
            asgd_identical = asgd_pin(k)
        snapshot_path = monitor.flush()

    top = next(m for m in modes
               if m["shards"] == k and m["local_workers"] == n_workers)
    out_doc = {
        "bench": "hierarchical_exchange",
        "backend": "cpu",
        "n_params": n_params,
        "n_leaves": len(base),
        "tree_mb_f32": round(tree_nbytes(base) / 1e6, 2),
        "alpha": alpha,
        "wire": {"compression": opts.compression, "dtype": opts.dtype},
        "modes": modes,
        "asgd_delta_sum_byte_identical": asgd_identical,
        "note": ("trajectory pins on the exact f32 lattice: ASGD "
                 "byte-identical to N direct same-version pushes; "
                 "EASGD equal to the closed-form same-version "
                 "composition (the direct-vs-hier delta is the "
                 "documented O(alpha^2) sequential-order effect)"),
    }
    tag = args.tag or "hierarchy_smoke"
    path = args.out or os.path.join(REPO, "artifacts",
                                    f"BENCH_{tag}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out_doc, f, indent=1)
    print(f"[bench_exchange] wrote {path} "
          f"(N={n_workers} K={k}: {top['wire_byte_reduction_x']}x "
          "fewer wire bytes/period)", flush=True)

    if not args.smoke:
        return 0
    ok = True
    if top["wire_byte_reduction_x"] < 3.9 and n_workers >= 4:
        print(f"[bench_exchange] FAIL: wire-byte reduction "
              f"{top['wire_byte_reduction_x']}x < 3.9x at "
              f"N={n_workers}", file=sys.stderr)
        ok = False
    if top["hierarchical"]["wire_bytes_per_period"] >= \
            top["direct"]["wire_bytes_per_period"]:
        print("[bench_exchange] FAIL: hierarchical wire bytes/period "
              "not below the direct baseline", file=sys.stderr)
        ok = False
    if top.get("easgd_closed_form_max_abs_diff", 1.0) != 0.0:
        print(f"[bench_exchange] FAIL: EASGD aggregate deviates from "
              f"the closed form on the exact lattice "
              f"(max abs diff "
              f"{top.get('easgd_closed_form_max_abs_diff')})",
              file=sys.stderr)
        ok = False
    if asgd_identical is not True:
        print("[bench_exchange] FAIL: ASGD delta-sum not "
              "byte-identical to N direct same-version pushes",
              file=sys.stderr)
        ok = False
    # monitor JSONL: the fan-in gauge + local_aggregate spans are the
    # operator-facing proof the aggregation plane actually served
    fan_in, agg_spans = None, 0
    if snapshot_path and os.path.exists(snapshot_path):
        with open(snapshot_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("name") == "aggregate/fan_in":
                    fan_in = rec.get("value")
                if (rec.get("name") == "span_ms"
                        and rec.get("labels", {}).get("name")
                        == "local_aggregate"):
                    agg_spans = rec.get("count", 0)
    if fan_in != float(n_workers):
        print(f"[bench_exchange] FAIL: aggregate/fan_in gauge is "
              f"{fan_in}, expected {n_workers} (monitor JSONL "
              f"{snapshot_path})", file=sys.stderr)
        ok = False
    if agg_spans <= 0:
        print("[bench_exchange] FAIL: no local_aggregate spans in the "
              f"monitor JSONL ({snapshot_path})", file=sys.stderr)
        ok = False
    print(f"[bench_exchange] hierarchy smoke {'PASS' if ok else 'FAIL'}",
          flush=True)
    return 0 if ok else 1


def _bucket_step_equivalence(mesh, B: int) -> bool:
    """Build a real bucketed TRAIN step (collectives embedded in the
    backward via the exchanger's boundary tags) and check it equals
    the B=1 step bit-for-bit over 3 iterations — the preflight-grade
    proof that bucketing changes scheduling, never numerics."""
    import jax
    import jax.numpy as jnp
    import optax

    from theanompi_tpu.parallel.bsp import TrainState, make_bsp_train_step
    from theanompi_tpu.parallel.exchanger import BSP_Exchanger
    from theanompi_tpu.parallel.mesh import shard_batch

    def loss(params, ms, batch, rng):
        x, y = batch
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        pred = h @ params["w2"] + params["b2"]
        l = jnp.mean((pred - y) ** 2)
        return l, (ms, {"loss": l})

    k = jax.random.split(jax.random.key(0), 2)
    params = {"w1": jax.random.normal(k[0], (6, 9)) * 0.3,
              "b1": jnp.zeros(9),
              "w2": jax.random.normal(k[1], (9, 2)) * 0.3,
              "b2": jnp.zeros(2)}
    tx = optax.sgd(0.05, momentum=0.9)
    rng_np = np.random.default_rng(5)
    batch = shard_batch(
        (rng_np.standard_normal((32, 6)).astype(np.float32),
         rng_np.standard_normal((32, 2)).astype(np.float32)), mesh)
    rng = jax.random.key(1)

    def run(buckets):
        ex = BSP_Exchanger(exchange_buckets=buckets, avg=True)
        step = make_bsp_train_step(loss, tx, mesh, ex, donate=False)
        s = TrainState.create(params, tx)
        for _ in range(3):
            s, _ = step(s, batch, rng)
        return [np.asarray(x) for x in jax.tree.leaves(s.params)]

    ref, out = run(1), run(B)
    return all(np.array_equal(a, b) for a, b in zip(ref, out))


def run_buckets(args) -> int:
    """``--buckets`` mode (ISSUE 13): drive the ~22.8M-param tree's
    IN-STEP bucketed exchange on the 8-device CPU mesh across bucket
    counts x wire dtypes.  Reports, per (dtype, B): the lowered
    program's collective count (B bucket collectives, by
    construction), per-bucket frame accounting (leaves + wire bytes
    from the shared plan every rank derives), and wall/exchange; plus
    the aggregate wall delta vs B=1 per dtype.  CPU walls bound the
    host-visible overhead of splitting the exchange, NOT the ICI
    overlap win — that is what the queued on-chip profile pair grades
    (artifacts/queue_xla_sweep_exps.json).

    ``--smoke`` is the preflight gate: sweeps only {1, B}, asserts the
    B=4-vs-B=1 train-step bit-identity and the bucket-count gauge in
    the monitor JSONL, exit 1 otherwise."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    os.environ.setdefault(
        "THEANOMPI_TPU_MONITOR",
        os.path.join(REPO, "artifacts", "bench_exchange_monitor"))

    from jax.sharding import PartitionSpec as P

    from theanompi_tpu import monitor
    from theanompi_tpu.parallel.exchanger import (
        BSP_Exchanger,
        _leaf_nbytes,
        bucket_ranges,
    )
    from theanompi_tpu.parallel.mesh import data_mesh

    bucket_list = sorted({int(b) for b in str(args.buckets).split(",")})
    if 1 not in bucket_list:
        bucket_list = [1] + bucket_list  # always carry the baseline
    smoke_b = max(bucket_list)
    n_exchanges = max(3, args.exchanges)
    tree = resnet50_like_tree(int(args.params))
    n_params = tree_params(tree)
    mesh = data_mesh(8)
    print(f"[bench_exchange] bucket mode: {n_params/1e6:.1f}M params, "
          f"{len(tree)} leaves, {tree_nbytes(tree)/1e6:.1f} MB f32, "
          f"B in {bucket_list}, 8-dev CPU mesh", flush=True)

    leaves = jax.tree.leaves(tree)
    sizes = [_leaf_nbytes(l) for l in leaves]
    modes = []
    dtypes = ("f32",) if args.smoke else ("f32", "bf16")
    with monitor.session():
        for dtype in dtypes:
            for B in bucket_list:
                ex = BSP_Exchanger(
                    exchange_dtype=None if dtype == "f32" else "bf16",
                    exchange_buckets=B, avg=True)
                fn = jax.jit(jax.shard_map(
                    ex.exchange, mesh=mesh, in_specs=P(),
                    out_specs=P(), check_vma=False))
                # one trace+lower serves both the collective count and
                # the executable (lower().compile() — calling fn()
                # after lower() would trace the whole program twice)
                t0 = time.monotonic()
                lowered = fn.lower(tree)
                txt = lowered.as_text()
                n_coll = (txt.count("stablehlo.all_reduce")
                          + txt.count("stablehlo.all_gather"))
                run = lowered.compile()
                out = run(tree)
                np.asarray(jax.tree.leaves(out)[0])  # fence
                compile_s = time.monotonic() - t0
                walls = []
                for _ in range(n_exchanges):
                    t0 = time.monotonic()
                    out = run(tree)
                    np.asarray(jax.tree.leaves(out)[0])
                    walls.append((time.monotonic() - t0) * 1e3)
                plan = bucket_ranges(sizes, B)
                wire_per_elem = 2 if dtype == "bf16" else 4
                per_bucket = [{
                    "bucket": i, "n_leaves": hi - lo,
                    "wire_bytes": wire_per_elem * sum(
                        int(l.size) for l in leaves[lo:hi]),
                } for i, (lo, hi) in enumerate(plan)]
                modes.append({
                    "dtype": dtype, "buckets": B,
                    "plan_buckets": len(plan),
                    "n_collectives_lowered": n_coll,
                    "n_exchanges": n_exchanges,
                    "wall_ms_mean": round(float(np.mean(walls)), 2),
                    "wall_ms_min": round(float(np.min(walls)), 2),
                    "compile_s": round(compile_s, 2),
                    "wire_bytes_total": sum(p["wire_bytes"]
                                            for p in per_bucket),
                    "per_bucket": per_bucket,
                })
                print(f"[bench_exchange] {dtype} B={B}: "
                      f"{modes[-1]['wall_ms_mean']:.0f} ms mean, "
                      f"{n_coll} collectives lowered", flush=True)
        equiv = _bucket_step_equivalence(mesh, smoke_b)
        snapshot_path = monitor.flush()

    delta = {}
    for dtype in dtypes:
        base = next(m for m in modes
                    if m["dtype"] == dtype and m["buckets"] == 1)
        delta[dtype] = {
            str(m["buckets"]):
                round(1.0 - m["wall_ms_mean"] / base["wall_ms_mean"], 4)
            for m in modes
            if m["dtype"] == dtype and m["buckets"] != 1}
    out_doc = {
        "bench": "bucketed_exchange",
        "backend": "cpu",
        "mesh_devices": 8,
        "n_params": n_params,
        "n_leaves": len(tree),
        "tree_mb_f32": round(tree_nbytes(tree) / 1e6, 2),
        "modes": modes,
        "aggregate_wall_delta_vs_b1": delta,
        "step_equivalence": {"buckets": smoke_b, "bit_identical": equiv},
        "note": ("CPU walls bound host-visible bucketing overhead only; "
                 "the ICI overlap win is graded by the queued on-chip "
                 "profile pair (xla_sweep_expected.md)"),
    }
    tag = args.tag or ("bucketed_smoke" if args.smoke
                       else f"bucketed_b{smoke_b}")
    path = args.out or os.path.join(REPO, "artifacts",
                                    f"BENCH_{tag}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out_doc, f, indent=1)
    print(f"[bench_exchange] wrote {path}", flush=True)

    if not args.smoke:
        return 0
    ok = True
    if not equiv:
        print(f"[bench_exchange] FAIL: B={smoke_b} train step is not "
              "bit-identical to B=1", file=sys.stderr)
        ok = False
    # the bucket-count gauge must have landed in the monitor JSONL
    # (operator-facing proof the bucket telemetry is live)
    found = False
    if snapshot_path and os.path.exists(snapshot_path):
        with open(snapshot_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("name") == "bsp/exchange_buckets":
                    found = True
    if not found:
        print("[bench_exchange] FAIL: bsp/exchange_buckets gauge "
              f"missing from monitor JSONL ({snapshot_path})",
              file=sys.stderr)
        ok = False
    print(f"[bench_exchange] bucket smoke {'PASS' if ok else 'FAIL'}",
          flush=True)
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--params", type=float, default=25.5e6,
                    help="target parameter count (~ResNet-50)")
    ap.add_argument("--exchanges", type=int, default=3,
                    help="timed exchanges per mode")
    ap.add_argument("--out", default=None,
                    help="output JSON (default artifacts/"
                         "BENCH_wire_<tag>.json)")
    ap.add_argument("--tag", default=None,
                    help="artifact tag (default: jax backend name)")
    ap.add_argument("--buckets", default=None, metavar="B[,B...]",
                    help="bucket mode (ISSUE 13): drive the in-step "
                         "bucketed gradient exchange on the 8-dev CPU "
                         "mesh across the given bucket counts (the "
                         "B=1 baseline is always added) x {f32,bf16}, "
                         "with per-bucket frame accounting and the "
                         "aggregate wall delta vs B=1; with --smoke "
                         "asserts the B-vs-1 step bit-identity + the "
                         "bucket gauge (the preflight bucketed gate). "
                         "Mutually exclusive with --shards")
    ap.add_argument("--shards", type=int, default=None, metavar="K",
                    help="shard mode: drive the tree against K real "
                         "shard processes (parallel/shards.py) and "
                         "report per-shard + aggregate bytes/wall vs "
                         "K=1; with --smoke also kills+recovers a "
                         "shard (the preflight 2-shard gate)")
    ap.add_argument("--local-workers", type=int, default=None,
                    metavar="N",
                    help="hierarchy mode (ISSUE 14): N co-located "
                         "workers behind one intra-host aggregator "
                         "(parallel/aggregate.py) vs N direct "
                         "exchanges, against --shards K real shard "
                         "processes (default 1) — per-period wire-byte "
                         "accounting + the ASGD byte-identity / EASGD "
                         "closed-form trajectory pins; with --smoke "
                         "asserts the >=3.9x byte reduction and the "
                         "fan-in gauge + local_aggregate spans (the "
                         "preflight hierarchy gate).  Mutually "
                         "exclusive with --buckets (hierarchical "
                         "aggregation is an async-rules plane; BSP's "
                         "in-step bucketed exchange refuses it — the "
                         "same matrix as the GOSGD/BSP launcher "
                         "refusals)")
    ap.add_argument("--shm-compare", action="store_true",
                    help="shared-memory-lane mode (ISSUE 20): in-band "
                         "vs shm legs across the exchange, ingest and "
                         "KV-page planes — identical workloads, fresh "
                         "server processes, sha256 byte-identity, a "
                         "mid-run force-disable fallback tail and a "
                         "SIGKILL-mid-lease sweep leg; writes "
                         "artifacts/BENCH_shm_smoke.json; with --smoke "
                         "asserts the >=25% exchange wall cut, the "
                         ">=1.3x ingest img/s lift, and zero leaked "
                         "segments.  Mutually exclusive with the other "
                         "legs")
    ap.add_argument("--smoke", action="store_true",
                    help="preflight gate: 1 exchange/mode, assert the "
                         "v2 byte win + the monitor gauge, exit 1 on "
                         "failure")
    args = ap.parse_args(argv)
    if args.shm_compare and (args.buckets is not None
                             or args.shards is not None
                             or args.local_workers is not None):
        raise FlagConflict(
            "--shm-compare is its own multi-plane leg (exchange + "
            "ingest + KV pages vs the shm lane) and drives its own "
            "fleet sizes — run --buckets/--shards/--local-workers "
            "separately")
    if args.buckets is not None and args.shards is not None:
        raise FlagConflict(
            "--buckets and --shards are mutually exclusive legs: the "
            "bucket leg measures the in-step SPMD exchange on a device "
            "mesh, the shard leg measures the wire exchange against "
            "real shard processes — run them separately")
    if args.local_workers is not None and args.buckets is not None:
        # the sibling of the --buckets/--shards conflict: hierarchical
        # aggregation applies to the async rules' WIRE exchange; BSP's
        # bucketed exchange runs inside the step program and refuses
        # it — exactly the GOSGD/BSP refusal matrix the launcher's
        # --local-aggregation enforces
        raise FlagConflict(
            "--local-workers and --buckets are mutually exclusive: "
            "hierarchical aggregation is an async-rules (EASGD/ASGD) "
            "wire plane, while the bucket leg measures BSP's in-step "
            "SPMD exchange — BSP (like GOSGD) refuses hierarchical "
            "aggregation (docs/DESIGN.md 'Hierarchical exchange')")
    if args.local_workers is not None and args.local_workers < 1:
        raise FlagConflict(
            f"--local-workers must be >= 1, got {args.local_workers}")
    if args.shm_compare:
        return run_shm_compare(args)
    if args.local_workers is not None:
        return run_hierarchy(args)
    if args.buckets is not None:
        return run_buckets(args)
    if args.shards is not None:
        return run_sharded(args)
    if args.smoke:
        args.exchanges = 1

    # the exchange service does its merge arithmetic in jax — keep it
    # off any real accelerator, this benchmarks the WIRE
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    os.environ.setdefault("THEANOMPI_TPU_SERVICE_KEY", "bench-exchange")
    mon_dir = os.environ.setdefault(
        "THEANOMPI_TPU_MONITOR",
        os.path.join(REPO, "artifacts", "bench_exchange_monitor"))

    from theanompi_tpu import monitor
    from theanompi_tpu.parallel.service import serve

    tree = resnet50_like_tree(int(args.params))
    n_params = tree_params(tree)
    print(f"[bench_exchange] tree: {n_params/1e6:.1f}M params, "
          f"{len(tree)} leaves, {tree_nbytes(tree)/1e6:.1f} MB f32",
          flush=True)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    ready, stop = threading.Event(), threading.Event()
    threading.Thread(target=serve,
                     args=("127.0.0.1", port, ready, stop),
                     daemon=True).start()
    if not ready.wait(30):
        print("[bench_exchange] service never came up", file=sys.stderr)
        return 1
    addr = f"127.0.0.1:{port}"

    results = []
    with monitor.session():
        for protocol, compression, dtype in MODES:
            os.environ["THEANOMPI_TPU_WIRE_COMPRESSION"] = compression
            os.environ["THEANOMPI_TPU_WIRE_DTYPE"] = dtype
            os.environ["THEANOMPI_TPU_WIRE_PROTOCOL"] = protocol
            try:
                r = measure_mode(addr, protocol, compression, dtype,
                                 tree, args.exchanges)
            finally:
                for k in ("THEANOMPI_TPU_WIRE_COMPRESSION",
                          "THEANOMPI_TPU_WIRE_DTYPE",
                          "THEANOMPI_TPU_WIRE_PROTOCOL"):
                    os.environ.pop(k, None)
            print(f"[bench_exchange] {protocol}/{compression}/{dtype}: "
                  f"{r['bytes_per_exchange']/1e6:.1f} MB/exchange, "
                  f"{r['wall_ms_mean']:.0f} ms mean", flush=True)
            results.append(r)
        snapshot_path = monitor.flush()
        stop.set()

    v1 = next(r for r in results if r["protocol"] == "v1")
    v2_bf16 = next(r for r in results if r["protocol"] == "v2"
                   and r["dtype"] == "bf16" and r["compression"] == "none")
    v2_f32 = next(r for r in results if r["protocol"] == "v2"
                  and r["dtype"] == "f32" and r["compression"] == "none")
    byte_cut = 1.0 - v2_bf16["bytes_per_exchange"] / v1["bytes_per_exchange"]
    out = {
        "bench": "wire_exchange",
        "backend": jax.default_backend(),
        "n_params": n_params,
        "n_leaves": len(tree),
        "tree_mb_f32": round(tree_nbytes(tree) / 1e6, 2),
        "modes": results,
        "v2_bf16_vs_v1_byte_cut": round(byte_cut, 4),
        "v2_f32_vs_v1_byte_overhead": round(
            v2_f32["bytes_per_exchange"] / v1["bytes_per_exchange"] - 1.0,
            4),
    }
    tag = args.tag or ("smoke" if args.smoke else jax.default_backend())
    path = args.out or os.path.join(REPO, "artifacts",
                                    f"BENCH_wire_{tag}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"[bench_exchange] wrote {path} "
          f"(v2+bf16 cuts {byte_cut:.1%} of v1 bytes)", flush=True)

    if args.smoke:
        ok = True
        # v2's raw f32 framing is byte-equal to pickle (both ship raw
        # buffers; v2 trades pickle's memo for a JSON skeleton) — the
        # byte win lives in the negotiated modes, so the gate checks
        # the LOSSLESS one (zlib/f32 must beat v1 with zero numeric
        # change) and the headline bf16 cut below
        v2_zlib = next(r for r in results if r["protocol"] == "v2"
                       and r["dtype"] == "f32"
                       and r["compression"] == "zlib")
        if v2_zlib["bytes_per_exchange"] >= v1["bytes_per_exchange"]:
            print("[bench_exchange] FAIL: v2-framed (zlib/f32, lossless) "
                  "does not beat v1-pickle on bytes/exchange",
                  file=sys.stderr)
            ok = False
        if byte_cut < 0.45:
            print(f"[bench_exchange] FAIL: v2+bf16 byte cut {byte_cut:.1%}"
                  " < 45%", file=sys.stderr)
            ok = False
        # the compression-ratio gauge must have landed in the monitor
        # JSONL (the operator-facing proof the wire accounting is live)
        found = False
        if snapshot_path and os.path.exists(snapshot_path):
            with open(snapshot_path) as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("name") == "service/wire_compression_ratio":
                        found = True
        if not found:
            print("[bench_exchange] FAIL: service/wire_compression_ratio "
                  f"gauge missing from monitor JSONL ({snapshot_path})",
                  file=sys.stderr)
            ok = False
        print(f"[bench_exchange] smoke {'PASS' if ok else 'FAIL'}",
              flush=True)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
