"""Connection-scaling benchmark for the RPC substrate (ISSUE 11).

Two legs, both against a REAL server process
(``python -m theanompi_tpu.parallel.service``) pinned to one core —
the honest front-door accounting: a serving host's event plane must be
cheap enough to leave the cores to the work.

* **connscale** — P client worker processes, each pipelining one
  in-flight pull on each of its C connections from a single thread
  (total concurrent authenticated connections 1→1000, every one with
  a request in flight), against the legacy thread-per-connection loop
  AND the selector event plane.  Reports aggregate pulls/s + p50/p99
  per point.  This is where thread-per-connection dies: at 600+
  in-flight connections the old loop is ~600 GIL-fighting server
  threads, while the event plane is one IO thread + a small executor
  pool.
* **convoy** — the PR 9 client-side collapse shape: N logical
  concurrent pullers in ONE client process pinned to ONE core with a
  GIL-holding compute thread (the trainer stand-in), comparing N
  dedicated sockets + N blocking recv threads (the old client) against
  ONE multiplexed socket + ONE pipelined thread
  (``rpc.MuxConnection``).  The committed bar is the PR 9 measured
  baseline — ~40 pulls/s at 12 recv threads on the one-core driver box
  (docs/DESIGN.md "Distributed ingest", measured pitfalls) — which the
  substrate must beat ≥10× at identical payload sizes.

``--smoke`` is the preflight gate (exit 1 on any miss):

* the selector loop sustains ≥1000 concurrent authenticated
  connections, every one with an in-flight request, at ≥1000 aggregate
  pulls/s with FLAT per-connection p99 (p99/conns at 1000 within 3× of
  the 8-connection point — i.e. pure fair-share queueing, no
  convoy-shaped blowup);
* at the 12-client convoy point the new substrate clears ≥10× the
  committed 40 pulls/s PR 9 baseline;
* the server's monitor JSONL carries the evidence
  (``rpc/connections_total`` ≥ the connection count,
  ``service/requests_total``, ``service/rpc_ms``).

Usage:
    python tools/bench_rpc.py                   # full sweep
    python tools/bench_rpc.py --smoke           # preflight gate
    python tools/bench_rpc.py --conns 8,200,1000 --loops selector
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import _bootstrap  # noqa: F401,E402  (tools/ sibling; pins JAX_PLATFORMS)

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the PR 9 measured collapse: ~1000→40 pulls/s at 12 recv threads in
#: one process on the one-core driver box (GIL convoy, 5 ms switch
#: interval per IO wake) — the committed baseline the ISSUE-11
#: acceptance bar is written against
PR9_CONVOY_BASELINE_PULLS_S = 40.0

SESSION = "bench-rpc"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pin(pid: int, cores: set[int] | None) -> None:
    if cores:
        try:
            os.sched_setaffinity(pid, cores)
        except (AttributeError, OSError):
            pass


# ---------------------------------------------------------------------------
# Server lifecycle
# ---------------------------------------------------------------------------


def start_server(loop: str, payload_floats: int,
                 server_cores: set[int] | None,
                 monitor_dir: str | None):
    """One real service process on ``loop``, seeded with the payload
    tree; returns (port, Popen, init_client)."""
    from theanompi_tpu.parallel.service import RemoteEASGD, _authkey

    _authkey(generate=True)  # one key for server + all workers
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               THEANOMPI_TPU_RPC_LOOP=loop,
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    if monitor_dir:
        env["THEANOMPI_TPU_MONITOR"] = monitor_dir
    srv = subprocess.Popen(
        [sys.executable, "-m", "theanompi_tpu.parallel.service",
         "--port", str(port), "--platform", "cpu"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    # readiness: the HMAC handshake answering is the signal
    deadline = time.monotonic() + 60
    init = None
    while init is None:
        try:
            tree = {"w": np.random.default_rng(0)
                    .random(payload_floats).astype(np.float32)}
            init = RemoteEASGD(f"127.0.0.1:{port}", tree, alpha=0.5,
                               session_id=SESSION)
        except Exception:
            if time.monotonic() > deadline:
                srv.terminate()
                raise RuntimeError(f"server ({loop}) never came up")
            time.sleep(0.2)
    _pin(srv.pid, server_cores)
    return port, srv, init


def stop_server(port: int, srv, init) -> None:
    from theanompi_tpu.parallel.service import ServiceClient

    init.close()
    try:
        c = ServiceClient(f"127.0.0.1:{port}")
        c.call("shutdown")
        c.close()
    except Exception:
        srv.terminate()
    srv.wait(timeout=30)


# ---------------------------------------------------------------------------
# connscale leg — worker subprocess protocol
# ---------------------------------------------------------------------------


def worker_main(args) -> int:
    """One client process: C authenticated connections, one in-flight
    pull pipelined on each, collected from a SINGLE thread via the
    select-style wait (no client-side thread convoy — the client must
    measure the server)."""
    from multiprocessing.connection import Client as MpClient
    from multiprocessing.connection import wait as conn_wait

    from theanompi_tpu.parallel import wire
    from theanompi_tpu.parallel.service import _authkey

    opts = wire.WireOptions()
    conns = []
    for _ in range(args.worker_conns):
        c = MpClient(("127.0.0.1", args.worker_port),
                     authkey=_authkey())
        c.send((wire.HELLO_OP, wire.hello_payload(opts)))
        status, _ = c.recv()
        assert status == "ok", "wire negotiation failed"
        conns.append(c)
    sys.stdout.write("READY\n")
    sys.stdout.flush()
    sys.stdin.readline()  # the go barrier
    req = ("easgd_get_center", SESSION)
    count, lat, sent = 0, [], {}
    stop = time.monotonic() + args.worker_dur
    for c in conns:
        wire.send_msg(c, req, opts)
        sent[c] = time.monotonic()
    while time.monotonic() < stop:
        for c in conn_wait(list(sent), timeout=0.2):
            status, _ = wire.recv_msg(c, opts)
            assert status == "ok"
            lat.append(time.monotonic() - sent.pop(c))
            count += 1
            wire.send_msg(c, req, opts)
            sent[c] = time.monotonic()
    lat.sort()
    out = {"count": count,
           "p50_ms": lat[len(lat) // 2] * 1e3 if lat else 0.0,
           "p99_ms": lat[int(len(lat) * 0.99)] * 1e3 if lat else 0.0}
    for c in conns:
        c.close()
    print("RESULT " + json.dumps(out))
    sys.stdout.flush()
    return 0


def connscale_point(loop: str, total_conns: int, procs: int,
                    dur_s: float, payload_floats: int,
                    server_cores: set[int] | None,
                    monitor_dir: str | None = None) -> dict:
    procs = min(procs, total_conns)
    port, srv, init = start_server(loop, payload_floats, server_cores,
                                   monitor_dir)
    try:
        per = total_conns // procs
        extra = total_conns - per * procs
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        ps = []
        for i in range(procs):
            n = per + (1 if i < extra else 0)
            ps.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--worker-port", str(port), "--worker-conns", str(n),
                 "--worker-dur", str(dur_s)],
                env=env, stdin=subprocess.PIPE,
                stdout=subprocess.PIPE, text=True))
        for p in ps:
            line = p.stdout.readline().strip()
            assert line == "READY", f"worker said {line!r}"
        t0 = time.monotonic()
        for p in ps:  # the go barrier: all conns exist before any pull
            p.stdin.write("go\n")
            p.stdin.flush()
        results = []
        for p in ps:
            for line in p.stdout:
                if line.startswith("RESULT "):
                    results.append(json.loads(line[7:]))
                    break
            p.wait(timeout=60)
        wall = time.monotonic() - t0
    finally:
        stop_server(port, srv, init)
    return {
        "loop": loop, "conns": total_conns, "procs": procs,
        "pulls_s": round(sum(r["count"] for r in results) / wall, 1),
        "p50_ms": round(max(r["p50_ms"] for r in results), 2),
        "p99_ms": round(max(r["p99_ms"] for r in results), 2),
    }


# ---------------------------------------------------------------------------
# convoy leg — the PR 9 client shape, in this process
# ---------------------------------------------------------------------------


def convoy_point(port: int, n: int, dur_s: float,
                 client_core: set[int] | None) -> dict:
    """Old client (N sockets, N blocking recv threads) vs new client
    (ONE mux socket, ONE pipelined thread) with a GIL-holding compute
    thread running — all in this process, optionally pinned to one
    core (the PR 9 driver-box conditions)."""
    from theanompi_tpu.parallel import rpc, wire
    from theanompi_tpu.parallel.service import ServiceClient

    before = (os.sched_getaffinity(0)
              if hasattr(os, "sched_getaffinity") else None)
    _pin(0, client_core)
    stop_compute = threading.Event()

    def compute():
        x = np.random.rand(64, 64)
        while not stop_compute.is_set():
            for _ in range(50):
                (x @ x).sum()
            sum(i * i for i in range(2000))

    ct = threading.Thread(target=compute, daemon=True,
                          name="bench-rpc-compute")
    ct.start()
    req = ("easgd_get_center", SESSION)

    def drive_threads() -> dict:
        clients = [ServiceClient(f"127.0.0.1:{port}")
                   for _ in range(n)]
        counts = [0] * n
        lat: list[float] = []
        llock = threading.Lock()
        stop_t = time.monotonic() + dur_s

        def run(i):
            c = clients[i]
            while time.monotonic() < stop_t:
                t0 = time.monotonic()
                c.call(*req)
                with llock:
                    lat.append(time.monotonic() - t0)
                counts[i] += 1

        ths = [threading.Thread(target=run, args=(i,))
               for i in range(n)]
        t0 = time.monotonic()
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        wall = time.monotonic() - t0
        for c in clients:
            c.close()
        lat.sort()
        return {"pulls_s": round(sum(counts) / wall, 1),
                "p50_ms": round(lat[len(lat) // 2] * 1e3, 2),
                "p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 2)}

    def drive_mux() -> dict:
        mc = rpc.MuxConnection(f"127.0.0.1:{port}")
        streams = [mc.connect_stream() for _ in range(n)]
        omap = dict(streams)
        count, lat, inflight = 0, [], {}
        stop_t = time.monotonic() + dur_s
        t0 = time.monotonic()
        for s, o in streams:
            wire.send_msg(s, req, o)
            inflight[s] = time.monotonic()
        while time.monotonic() < stop_t:
            for s in rpc.wait_readable(list(inflight), 0.05):
                wire.recv_msg(s, omap[s])
                lat.append(time.monotonic() - inflight.pop(s))
                count += 1
                wire.send_msg(s, req, omap[s])
                inflight[s] = time.monotonic()
        wall = time.monotonic() - t0
        for s, _ in streams:
            s.close()
        mc.close()
        lat.sort()
        return {"pulls_s": round(count / wall, 1),
                "p50_ms": round(lat[len(lat) // 2] * 1e3, 2),
                "p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 2)}

    try:
        old = drive_threads()
        new = drive_mux()
    finally:
        stop_compute.set()
        ct.join(timeout=5)
        if before is not None:
            _pin(0, before)
    return {"n_clients": n,
            "old_threads_per_conn": old,
            "new_mux_pipelined": new,
            "committed_pr9_baseline_pulls_s":
                PR9_CONVOY_BASELINE_PULLS_S,
            "recovery_vs_pr9_baseline": round(
                new["pulls_s"] / PR9_CONVOY_BASELINE_PULLS_S, 1)}


# ---------------------------------------------------------------------------
# soak leg — mux byte-identity under sustained load
# ---------------------------------------------------------------------------


def run_soak(args) -> int:
    """``--soak``: the mux byte-identity pins under sustained load —
    the CPU gate behind flipping ``THEANOMPI_TPU_SHARD_MUX`` /
    ``THEANOMPI_TPU_INGEST_MUX`` defaults to ON (ROADMAP item 6
    leftover).

    Per loop (selector AND threaded): a real server seeded with a
    known tree; several ``rpc.MuxConnection`` transports, each shared
    by multiple ``ServiceClient`` streams (the shard-router shape —
    data + control streams on one socket); reader threads hammer
    ``easgd_get_center`` for ``--dur`` seconds comparing EVERY reply
    bitwise to the seeded tree, while writer threads interleave large
    gossip push/drain frames on the SAME transports.  The threaded
    loop grants no mux, so the identical client code must silently
    fall back to dedicated sockets and still hold identity — that
    fallback is what makes the ON default safe against old servers.
    Exit 1 on any byte mismatch or transport error."""
    from theanompi_tpu.parallel import rpc
    from theanompi_tpu.parallel.service import (
        RemoteGossipHub,
        ServiceClient,
    )

    payload_floats = args.payload_kb * 256
    ref = np.random.default_rng(0).random(payload_floats) \
        .astype(np.float32)
    ref_bytes = ref.tobytes()
    n_transports, streams_per = 3, 4
    results = {}
    for loop in args.loops.split(","):
        port, srv, init = start_server(loop, payload_floats, None, None)
        stop_t = time.monotonic() + args.dur
        counts = {"reads": 0, "writes": 0}
        errors: list[str] = []
        mismatches = [0]
        lock = threading.Lock()
        try:
            transports = [rpc.MuxConnection(f"127.0.0.1:{port}")
                          for _ in range(n_transports)]
            readers = [ServiceClient(f"127.0.0.1:{port}", transport=t)
                       for t in transports for _ in range(streams_per)]
            # one writer hub PER mux transport: the large gossip
            # frames must chunk-interleave with the identity-checked
            # reads on the SAME sockets — that interleaving is exactly
            # the hazard the mux-ON default flip is gated on
            hubs = [RemoteGossipHub(f"127.0.0.1:{port}", 2,
                                    session_id=SESSION + "-soak",
                                    transport=t) for t in transports]

            def read_loop(c):
                n = 0
                try:
                    while time.monotonic() < stop_t:
                        out = c.call("easgd_get_center", SESSION)
                        if np.asarray(out["w"]).tobytes() != ref_bytes:
                            with lock:
                                mismatches[0] += 1
                        n += 1
                except Exception as e:
                    with lock:
                        errors.append(f"reader: {type(e).__name__}: {e}")
                with lock:
                    counts["reads"] += n

            def write_loop(hub):
                # big frames both directions on the shared sockets:
                # gossip push/drain rides its OWN store kind, so the
                # easgd center the readers pin stays untouched
                n = 0
                tree = {"g": ref[: payload_floats // 4]}
                try:
                    while time.monotonic() < stop_t:
                        hub.push(1, tree, 0.01)
                        hub.drain(1)
                        n += 1
                except Exception as e:
                    with lock:
                        errors.append(f"writer: {type(e).__name__}: {e}")
                with lock:
                    counts["writes"] += n

            ths = [threading.Thread(target=read_loop, args=(c,),
                                    daemon=True) for c in readers] \
                + [threading.Thread(target=write_loop, args=(h,),
                                    daemon=True) for h in hubs]
            t0 = time.monotonic()
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            wall = time.monotonic() - t0
            muxed = any(getattr(t, "_mux", False) for t in transports)
            for c in readers:
                c.close()
            for h in hubs:
                h.close()
            for t in transports:
                t.close()
        finally:
            stop_server(port, srv, init)
        results[loop] = {
            "reads": counts["reads"], "writes": counts["writes"],
            "reads_per_s": round(counts["reads"] / wall, 1),
            "byte_mismatches": mismatches[0],
            "errors": errors[:5],
            "mux_granted": muxed,
            "streams": n_transports * (streams_per + 1),
            "dur_s": round(wall, 1),
        }
        print(f"[soak] loop={loop:8s} {counts['reads']} identity-"
              f"checked reads ({results[loop]['reads_per_s']}/s), "
              f"{counts['writes']} interleaved push/drain rounds, "
              f"mux_granted={muxed}, mismatches={mismatches[0]}, "
              f"errors={len(errors)}", flush=True)

    failures = []
    for loop, r in results.items():
        if r["byte_mismatches"]:
            failures.append(f"{loop}: {r['byte_mismatches']} byte "
                            "mismatches")
        if r["errors"]:
            failures.append(f"{loop}: transport errors {r['errors']}")
        if not r["reads"] or not r["writes"]:
            failures.append(f"{loop}: no sustained load "
                            f"(reads={r['reads']}, "
                            f"writes={r['writes']})")
    if "selector" in results and not results["selector"]["mux_granted"]:
        failures.append("selector loop did not grant mux — the soak "
                        "never exercised stream multiplexing")
    if "threaded" in results and results["threaded"]["mux_granted"]:
        failures.append("threaded loop granted mux?! the dedicated-"
                        "socket fallback went unexercised")
    out_doc = {"bench": "rpc_soak", "payload_kb": args.payload_kb,
               "loops": results,
               "failures": failures, "ok": not failures}
    out = args.out or os.path.join(REPO, "artifacts",
                                   "BENCH_rpc_soak.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(out_doc, f, indent=1)
    for fmsg in failures:
        print(f"[soak] FAIL: {fmsg}", file=sys.stderr)
    print(f"[soak] {'PASS' if not failures else 'FAIL'} -> {out}",
          flush=True)
    return 0 if not failures else 1


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="preflight gate: 1000-conn flat-p99 + convoy "
                         "recovery assertions, exit 1 on any miss")
    ap.add_argument("--soak", action="store_true",
                    help="mux byte-identity soak (the gate behind the "
                         "SHARD_MUX/INGEST_MUX ON defaults): muxed "
                         "streams hammer identity-checked reads with "
                         "interleaved large frames for --dur seconds "
                         "on BOTH loops (threaded = the dedicated-"
                         "socket fallback), exit 1 on any mismatch")
    ap.add_argument("--conns", default=None,
                    help="comma-separated connscale points "
                         "(default smoke: 8,1000; full: "
                         "1,8,48,200,600,1000)")
    ap.add_argument("--loops", default="threaded,selector")
    ap.add_argument("--procs", type=int, default=4,
                    help="client worker processes per point")
    ap.add_argument("--dur", type=float, default=5.0,
                    help="seconds per measured point")
    ap.add_argument("--payload-kb", type=int, default=256,
                    help="pull payload (f32 tree) for connscale; the "
                         "convoy leg always uses 1024 (the PR 9 "
                         "~1 MB batch-pull shape)")
    ap.add_argument("--convoy-clients", type=int, default=12,
                    help="the PR 9 measured collapse point")
    ap.add_argument("--server-core", type=int, default=None,
                    help="pin the server to ONE core (default: the "
                         "highest available; -1 disables pinning)")
    ap.add_argument("--out", default=None,
                    help="result JSON path (default "
                         "artifacts/BENCH_rpc_smoke.json with --smoke)")
    # worker mode (internal)
    ap.add_argument("--worker-port", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--worker-conns", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--worker-dur", type=float, default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.worker_port is not None:
        return worker_main(args)
    if args.soak:
        return run_soak(args)

    ncpu = os.cpu_count() or 1
    if args.server_core == -1:
        server_cores = None
        client_core = None
    else:
        core = (args.server_core if args.server_core is not None
                else ncpu - 1)
        server_cores = {core}
        client_core = {0} if ncpu > 1 else None
    points = [int(x) for x in (args.conns or (
        "8,1000" if args.smoke else "1,8,48,200,600,1000")).split(",")]
    loops = args.loops.split(",")
    payload_floats = args.payload_kb * 256  # f32 per KB

    result = {
        "host": {"cpus": ncpu, "server_cores": sorted(server_cores)
                 if server_cores else "unpinned"},
        "payload_kb": args.payload_kb,
        "connscale": [],
        "convoy": None,
        "committed_pr9_baseline_pulls_s": PR9_CONVOY_BASELINE_PULLS_S,
    }

    mon_dir = tempfile.mkdtemp(prefix="bench_rpc_mon_")
    try:
        for conns in points:
            for loop in loops:
                use_mon = (mon_dir if loop == "selector"
                           and conns == max(points) else None)
                r = connscale_point(loop, conns, args.procs, args.dur,
                                    payload_floats, server_cores,
                                    monitor_dir=use_mon)
                result["connscale"].append(r)
                print(f"[connscale] conns={conns:5d} loop={loop:8s} "
                      f"{r['pulls_s']:9.1f} pulls/s "
                      f"p50={r['p50_ms']:8.1f}ms "
                      f"p99={r['p99_ms']:8.1f}ms", flush=True)

        # convoy leg: selector server (unpinned interference is the
        # point on the CLIENT side; server stays pinned), 1 MB pulls
        port, srv, init = start_server("selector", 262144,
                                       server_cores, None)
        try:
            result["convoy"] = convoy_point(port, args.convoy_clients,
                                            args.dur, client_core)
        finally:
            stop_server(port, srv, init)
        cv = result["convoy"]
        print(f"[convoy] n={cv['n_clients']} old(threads/conn): "
              f"{cv['old_threads_per_conn']['pulls_s']} pulls/s | "
              f"new(mux 1-thread): "
              f"{cv['new_mux_pipelined']['pulls_s']} pulls/s | "
              f"{cv['recovery_vs_pr9_baseline']}x the committed "
              f"{PR9_CONVOY_BASELINE_PULLS_S:.0f} pulls/s PR9 "
              "baseline", flush=True)

        # monitor JSONL evidence from the biggest selector point
        evidence = {}
        for fn in os.listdir(mon_dir):
            if fn.startswith("metrics_") and fn.endswith(".jsonl"):
                recs = [json.loads(l)
                        for l in open(os.path.join(mon_dir, fn))]
                for r in recs:
                    if r["name"] == "rpc/connections_total":
                        evidence["rpc_connections_total"] = \
                            evidence.get("rpc_connections_total", 0) \
                            + r["value"]
                    if (r["name"] == "service/requests_total"
                            and r["labels"].get("op")
                            == "easgd_get_center"):
                        evidence["requests_total"] = \
                            evidence.get("requests_total", 0) \
                            + r["value"]
                    if (r["name"] == "service/rpc_ms"
                            and r["labels"].get("op")
                            == "easgd_get_center"):
                        evidence["server_rpc_p99_ms"] = r.get("p99")
        result["monitor_evidence"] = evidence

        if args.smoke:
            failures = []
            sel = {r["conns"]: r for r in result["connscale"]
                   if r["loop"] == "selector"}
            top = max(sel)
            # the committed artifact must carry the full 1000; an
            # explicit --conns (preflight's quicker >=200 leg) lowers
            # the floor, not the flatness/recovery bars
            min_top = 1000 if args.conns is None else 200
            if top < min_top:
                failures.append(f"top selector point is {top} conns; "
                                f"the smoke bar is {min_top}")
            if sel[top]["pulls_s"] < 1000:
                failures.append(
                    f"selector at {top} conns: "
                    f"{sel[top]['pulls_s']} pulls/s < 1000")
            lo = min(sel)
            flat = ((sel[top]["p99_ms"] / top)
                    / max(sel[lo]["p99_ms"] / lo, 1e-9))
            if flat > 3.0:
                failures.append(
                    f"p99-per-connection not flat: {top}-conn point "
                    f"is {flat:.1f}x the {lo}-conn point (bar 3x)")
            result["p99_per_conn_flatness"] = round(flat, 2)
            new = cv["new_mux_pipelined"]["pulls_s"]
            if new < 10 * PR9_CONVOY_BASELINE_PULLS_S:
                failures.append(
                    f"convoy recovery {new} pulls/s < 10x the "
                    f"committed {PR9_CONVOY_BASELINE_PULLS_S} "
                    "baseline")
            if evidence.get("rpc_connections_total", 0) < top:
                failures.append(
                    "monitor evidence missing: rpc/connections_total "
                    f"= {evidence.get('rpc_connections_total')} < "
                    f"{top}")
            if not evidence.get("requests_total"):
                failures.append("monitor evidence missing: "
                                "service/requests_total")
            result["smoke"] = {"failures": failures,
                               "ok": not failures}
            for f in failures:
                print(f"[smoke] FAIL: {f}", flush=True)
    finally:
        shutil.rmtree(mon_dir, ignore_errors=True)

    out = args.out or (os.path.join(REPO, "artifacts",
                                    "BENCH_rpc_smoke.json")
                       if args.smoke else None)
    if out:
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[bench_rpc] wrote {out}", flush=True)
    else:
        print(json.dumps(result, indent=2))
    if args.smoke and result["smoke"]["failures"]:
        print("BENCH_RPC SMOKE: FAIL", flush=True)
        return 1
    if args.smoke:
        print("BENCH_RPC SMOKE: GREEN", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
