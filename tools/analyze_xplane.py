"""Op-level device-time account from a JAX profiler xplane proto.

Round-3's trace analysis dead-ended because ``tools/analyze_trace.py``
reads only the Perfetto ``trace.json.gz`` export, which on the axon
plugin carries host threads but NO device timeline — the round-4
verdict asked whether the committed ``vm.xplane.pb`` held device
planes that simply weren't parsed.  It does: ``/device:TPU:0`` with an
"XLA Ops" line (17 790 events for 5 ResNet steps), each event carrying
``hlo_category``, ``flops``, ``bytes_accessed``, and the HLO text with
shapes.  This tool turns that into the per-op MFU account (SURVEY §6 /
§7 hard-part 2): where every slice of the step goes, at what measured
TF/s and GB/s, and how close each slice sits to its own roofline.

Needs the TF tsl xplane proto bindings
(``tensorflow.tsl.profiler.protobuf.xplane_pb2`` — present in this
image's tensorflow); the aggregation itself is pure Python over plain
dicts so it unit-tests without tensorflow.

Usage:
    python tools/analyze_xplane.py artifacts/tpu_trace [--out report.json]

The positional argument is a profile dir (searched recursively for
``*.xplane.pb``) or a single ``.xplane.pb`` file.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from collections import defaultdict

# -- pure aggregation core (unit-testable without tensorflow) -------------

_SHAPE_RE = re.compile(r"\[(\d+),(\d+),(\d+),(\d+)\]")


def conv_spatial_bucket(hlo_text: str) -> str:
    """Bucket a conv fusion by the first NHWC shape in its HLO text —
    a proxy for ResNet stage (56/28/14/7 spatial).  'other' when no
    4-D shape appears."""
    m = _SHAPE_RE.search(hlo_text)
    if not m:
        return "other"
    n, h, w, c = (int(g) for g in m.groups())
    return f"{h}x{w}x{c}"


def aggregate(events: list[dict], n_steps: int) -> dict:
    """events: [{name, display, category, dur_ps, flops, bytes}] over
    ``n_steps`` captured steps.  Returns {categories, conv_buckets,
    top_ops, totals} with per-STEP ms and measured rates."""
    cats = defaultdict(lambda: [0, 0, 0, 0])       # dur, flops, bytes, n
    convs = defaultdict(lambda: [0, 0, 0, 0])
    ops = defaultdict(lambda: [0, 0, 0, 0, ""])
    for e in events:
        for table, key in ((cats, e["category"]),
                           (ops, e["display"])):
            a = table[key]
            a[0] += e["dur_ps"]
            a[1] += e["flops"]
            a[2] += e["bytes"]
            a[3] += 1
            if table is ops:
                a[4] = e["category"]
        if e["category"] == "convolution fusion":
            a = convs[conv_spatial_bucket(e["name"])]
            a[0] += e["dur_ps"]
            a[1] += e["flops"]
            a[2] += e["bytes"]
            a[3] += 1

    def row(d, f, b, n, *extra):
        ms = d / 1e9 / n_steps
        sec = d / 1e12
        return {
            "ms_per_step": round(ms, 3),
            "tflops_per_s": round(f / sec / 1e12, 1) if d else 0.0,
            "gbytes_per_s": round(b / sec / 1e9, 1) if d else 0.0,
            "events_per_step": n // n_steps,
            **({"category": extra[0]} if extra else {}),
        }

    total_dur = sum(v[0] for v in cats.values())
    total_flops = sum(v[1] for v in cats.values())
    return {
        "totals": {
            "device_busy_ms_per_step": round(total_dur / 1e9 / n_steps, 3),
            "achieved_tflops_per_s": round(
                total_flops / (total_dur / 1e12) / 1e12, 1)
            if total_dur else 0.0,
            "n_steps": n_steps,
        },
        "categories": {
            k: {**row(*v), "pct": round(100 * v[0] / total_dur, 1)}
            for k, v in sorted(cats.items(), key=lambda kv: -kv[1][0])
        },
        "conv_buckets": {
            k: {**row(*v), "pct": round(100 * v[0] / total_dur, 1)}
            for k, v in sorted(convs.items(), key=lambda kv: -kv[1][0])
        },
        "top_ops": [
            {"op": k, **row(*v[:4], v[4]),
             "pct": round(100 * v[0] / total_dur, 1)}
            for k, v in sorted(ops.items(), key=lambda kv: -kv[1][0])[:25]
        ],
    }


def roofline(report: dict, peak_tflops: float, peak_hbm_gbps: float) -> dict:
    """Per-slice roofline adjudication: a slice running at X TF/s while
    streaming Y GB/s has an HBM-implied ceiling of
    X * (peak_hbm / Y) — if that ceiling is close to X, the slice is
    bandwidth-bound and X is ~its achievable rate at this arithmetic
    intensity."""
    out = {}
    for k, c in report["categories"].items():
        gbs, tfs = c["gbytes_per_s"], c["tflops_per_s"]
        hbm_frac = gbs / peak_hbm_gbps if peak_hbm_gbps else 0.0
        implied = tfs / hbm_frac if hbm_frac > 0 else float("inf")
        out[k] = {
            "hbm_fraction": round(hbm_frac, 3),
            "mxu_fraction": round(tfs / peak_tflops, 3)
            if peak_tflops else 0.0,
            "hbm_implied_tflops_ceiling": (round(implied, 1)
                                           if implied != float("inf")
                                           else None),
        }
    return out


# -- proto extraction -----------------------------------------------------

def _load_xspace(path: str):
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError as e:  # pragma: no cover
        raise SystemExit(
            "needs tensorflow's tsl xplane proto bindings "
            f"(import failed: {e}); on a box without tensorflow, copy "
            "the .xplane.pb to one that has it") from e
    space = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        space.ParseFromString(f.read())
    return space


def extract_device_events(space) -> tuple[list[dict], int, dict]:
    """(events, n_steps, device_info) from the first TPU/GPU device
    plane.  Events come from the 'XLA Ops' line; n_steps from the
    'XLA Modules' line (module executions captured)."""
    plane = None
    for p in space.planes:
        if "/device:" in p.name and "CUSTOM" not in p.name and any(
                ln.events for ln in p.lines):
            plane = p
            break
    if plane is None:
        raise SystemExit(
            "no device plane with events in this xplane — the capture "
            "has host threads only (the round-3 failure mode); re-trace "
            "with the step running on the device backend")
    sm, em = plane.stat_metadata, plane.event_metadata

    def stat_val(s):
        return (s.str_value or s.int64_value or s.uint64_value
                or s.double_value)

    info = {"plane": plane.name}
    for s in plane.stats:
        n = sm[s.metadata_id].name
        if n in ("device_type_string", "peak_teraflops_per_second",
                 "peak_hbm_bw_gigabytes_per_second"):
            info[n] = stat_val(s)

    lines = {ln.name: ln for ln in plane.lines}
    n_steps = len(lines["XLA Modules"].events) if "XLA Modules" in lines \
        else max(1, len(lines.get("Steps", ()) and lines["Steps"].events))
    events = []
    for e in lines["XLA Ops"].events:
        md = em[e.metadata_id]
        st = {sm[s.metadata_id].name: stat_val(s) for s in md.stats}
        events.append({
            "name": md.name,
            "display": md.display_name,
            "category": st.get("hlo_category", "?"),
            "dur_ps": e.duration_ps,
            "flops": st.get("flops", 0) or 0,
            "bytes": st.get("bytes_accessed", 0) or 0,
        })
    return events, n_steps, info


def find_xplane(path: str) -> str:
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"),
                            recursive=True))
    if not hits:
        raise SystemExit(f"no *.xplane.pb under {path}")
    return hits[-1]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("path", help="profile dir or .xplane.pb file")
    ap.add_argument("--out", default=None, help="write full JSON here")
    args = ap.parse_args()

    pb = find_xplane(args.path)
    events, n_steps, info = extract_device_events(_load_xspace(pb))
    report = aggregate(events, n_steps)
    peak_tf = float(info.get("peak_teraflops_per_second", 0) or 0)
    peak_bw = float(info.get("peak_hbm_bw_gigabytes_per_second", 0) or 0)
    rl = roofline(report, peak_tf, peak_bw)

    t = report["totals"]
    print(f"# {info.get('device_type_string', '?')} — peak "
          f"{peak_tf:.0f} TF/s, HBM {peak_bw:.0f} GB/s ({info['plane']})")
    print(f"# {t['n_steps']} steps captured, device-busy "
          f"{t['device_busy_ms_per_step']} ms/step, achieved "
          f"{t['achieved_tflops_per_s']} TF/s over device-busy time")
    print(f"{'category':<26}{'ms/step':>9}{'%':>7}{'TF/s':>8}{'GB/s':>8}"
          f"{'%HBM':>7}{'ceilTF/s':>10}")
    for k, c in report["categories"].items():
        r = rl[k]
        ceil = r["hbm_implied_tflops_ceiling"]
        print(f"{k[:25]:<26}{c['ms_per_step']:9.3f}{c['pct']:7.1f}"
              f"{c['tflops_per_s']:8.1f}{c['gbytes_per_s']:8.0f}"
              f"{100 * r['hbm_fraction']:7.1f}"
              f"{(f'{ceil:10.1f}' if ceil else '         -')}")
    print(f"\n{'conv bucket (HxWxC)':<26}{'ms/step':>9}{'%':>7}"
          f"{'TF/s':>8}{'GB/s':>8}")
    for k, c in report["conv_buckets"].items():
        print(f"{k:<26}{c['ms_per_step']:9.3f}{c['pct']:7.1f}"
              f"{c['tflops_per_s']:8.1f}{c['gbytes_per_s']:8.0f}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"device": info, "report": report,
                       "roofline": rl, "source": pb}, f, indent=1)
        print(f"\nwrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
