"""Op-level device-time account from a JAX profiler xplane proto.

Round-3's trace analysis dead-ended because ``tools/analyze_trace.py``
reads only the Perfetto ``trace.json.gz`` export, which on the axon
plugin carries host threads but NO device timeline — the round-4
verdict asked whether the committed ``vm.xplane.pb`` held device
planes that simply weren't parsed.  It does: ``/device:TPU:0`` with an
"XLA Ops" line (17 790 events for 5 ResNet steps), each event carrying
``hlo_category``, ``flops``, ``bytes_accessed``, and the HLO text with
shapes.  This tool turns that into the per-op MFU account (SURVEY §6 /
§7 hard-part 2): where every slice of the step goes, at what measured
TF/s and GB/s, and how close each slice sits to its own roofline.

Needs the TF tsl xplane proto bindings
(``tensorflow.tsl.profiler.protobuf.xplane_pb2`` — present in this
image's tensorflow); the aggregation itself is pure Python over plain
dicts so it unit-tests without tensorflow.

Usage:
    python tools/analyze_xplane.py artifacts/tpu_trace [--out report.json]

The positional argument is a profile dir (searched recursively for
``*.xplane.pb``) or a single ``.xplane.pb`` file.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from collections import defaultdict

# -- pure aggregation core (unit-testable without tensorflow) -------------

_SHAPE_RE = re.compile(r"\[(\d+),(\d+),(\d+),(\d+)\]")

#: Rows whose total duration per step is below this are too short for the
#: flops/bytes counters to produce meaningful rates (the r4 account printed
#: 5.77e6 GB/s for async-start); their rates are suppressed and flagged.
SUB_RESOLUTION_MS = 0.05


def hlo_output_part(hlo_text: str) -> str:
    """The output-shape side of ``%name = <shapes> op(operands…)`` —
    text before the operand list (shared with tools/fusion_deepdive.py
    so the two tools can't silently diverge on output parsing)."""
    return hlo_text.split(" fusion(")[0] if " fusion(" in hlo_text \
        else hlo_text.split("(")[0]


_COPY_SHAPE = re.compile(r"copy-done\(\((\w+)\[([\d,]*)\]")
_DTYPE_BYTES = {"f32": 4, "bf16": 2, "s32": 4, "u32": 4, "f16": 2,
                "s8": 1, "u8": 1, "pred": 1}
#: one full shape token inside an HLO tuple: dtype[dims]{layout}
_SHAPE_TOK = re.compile(r"(\w+)\[([\d,]*)\]\{([^}]*)\}")


def _size_class(nbytes: int) -> str:
    """'param_vec' (<=64 KiB — BN scales, biases, optimizer scalars),
    'kernel' (<=4 MiB), 'activation' (larger) — THE size thresholds,
    shared by copy_size_class and attribute_copies so the two views
    cannot classify one event differently."""
    if nbytes <= 64 * 1024:
        return "param_vec"
    if nbytes <= 4 * 1024 * 1024:
        return "kernel"
    return "activation"


def _shape_nbytes(dtype: str, dims: str) -> int:
    """Bytes of one ``dtype[dims]`` shape token — THE byte math for
    every copy view in this file."""
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def copy_size_class(name: str) -> str:
    """Size class of the tensor a copy-done materialises, parsed from
    the copy's tuple-shape text; 'unknown' when no copy tuple is
    present.  (Shared with tools/fusion_deepdive.py.)"""
    m = _COPY_SHAPE.search(name)
    if not m:
        return "unknown"
    return _size_class(_shape_nbytes(m.group(1), m.group(2)))


def shrink_tf_op(tf_op: str) -> str:
    """'jit(shard_step)/jvp(ResNet)/BottleneckBlock_1/add:' ->
    'fwd/BottleneckBlock_1/add' (strip jit wrapper, fold jvp/transpose
    into fwd/bwd, drop trailing colon).  Empty in -> empty out, so
    callers' ``or``-fallbacks to the display name still fire.
    (Shared with tools/fusion_deepdive.py.)"""
    if not tf_op:
        return ""
    s = tf_op.rstrip(":")
    direction = "bwd" if "transpose(" in s else "fwd"
    s = re.sub(r"jit\([^)]*\)/", "", s)
    s = re.sub(r"(transpose\(|jvp\(|\))", "", s)
    return f"{direction}/{s}"


def copy_endpoints(name: str) -> tuple[str, str, str, int]:
    """(direction, shape, dest_layout, nbytes) of one copy-done event.

    The r3 capture's copy events carry NO tf_op (the source-op stat is
    empty on every one of the 6 670), so attribution has to come from
    the HLO text itself: a copy-start's operand tuple is ``(dest, src,
    context)`` and the memory-space suffix on the layouts says which
    way the bytes flow — ``S(1)`` is the compiler-managed alternate
    memory (MSA/VMEM prefetch space):

    - dest in S(1): ``prefetch`` — HBM -> on-chip staging of a buffer
      the scheduler wants resident before use (the 1 146 tiny
      param-vector copies of the account);
    - src in S(1): ``writeback`` — staged/produced on-chip, copied out
      to a fresh HBM buffer.  A big batch-led shape here is the smoking
      gun for a live input buffer XLA could not alias (donation gap);
    - neither: ``move`` — an HBM->HBM copy (layout change or alias
      materialization).
    """
    m = re.search(r"copy-done\(\((.*)", name)
    toks = _SHAPE_TOK.findall(m.group(1)) if m else []
    if len(toks) < 2:
        return "unknown", "?", "", 0
    (d_dt, d_dims, d_lay), (_s_dt, _s_dims, s_lay) = toks[0], toks[1]
    nbytes = _shape_nbytes(d_dt, d_dims)
    if "S(1)" in d_lay:
        direction = "prefetch"
    elif "S(1)" in s_lay:
        direction = "writeback"
    else:
        direction = "move"
    return direction, f"{d_dt}[{d_dims}]", d_lay, nbytes


def attribute_copies(events: list[dict], n_steps: int) -> dict:
    """The copy-done account: every copy event attributed to what it
    copies (direction x size-class x shape), sorted by time.

    The r4 account flags 2.37 ms/step across 1 334 copy-done events as
    near-zero-FLOP residue; this names each slice so the fix (buffer
    donation, layout pinning) can be targeted and the after-capture
    diffed per row (tools/xla_sweep.py consumes two of these).
    """
    rows = defaultdict(lambda: [0, 0, 0])      # dur_ps, bytes, n
    done_dur = done_n = start_dur = start_n = 0
    for e in events:
        if e["category"] == "copy-start":
            start_dur += e["dur_ps"]
            start_n += 1
            continue
        if e["category"] != "copy-done":
            continue
        done_dur += e["dur_ps"]
        done_n += 1
        direction, shape, _lay, nbytes = copy_endpoints(e["name"])
        cls = _size_class(nbytes) if direction != "unknown" \
            else "unknown"
        a = rows[(direction, cls, shape)]
        a[0] += e["dur_ps"]
        a[1] += nbytes
        a[2] += 1

    out_rows = []
    for (direction, cls, shape), (dur, nbytes, n) in sorted(
            rows.items(), key=lambda kv: -kv[1][0]):
        ms = dur / 1e9 / n_steps
        out_rows.append({
            "producer": f"{direction}:{cls}:{shape}",
            "ms_per_step": round(ms, 3),
            "events_per_step": n // n_steps,
            "us_per_event": round(dur / 1e6 / n, 2) if n else 0.0,
            "mbytes_per_step": round(nbytes / 1e6 / n_steps, 2),
            "pct_of_copy_done": round(100 * dur / done_dur, 1)
            if done_dur else 0.0,
        })
    return {
        "copy_done_ms_per_step": round(done_dur / 1e9 / n_steps, 3),
        "copy_done_events_per_step": done_n // n_steps,
        "copy_start_ms_per_step": round(start_dur / 1e9 / n_steps, 3),
        "copy_start_events_per_step": start_n // n_steps,
        "rows": out_rows,
    }


def conv_spatial_bucket(hlo_text: str, tf_op: str = "") -> str:
    """Bucket a conv fusion by its ACTIVATION shape + pass kind.

    The round-4 account used the first 4-D shape in the HLO text, which
    for weight-gradient convs is the *kernel* (e.g. ``[1,1,64,256]``) —
    ~8%% of the step was mis-attributed to kernel-shaped "activation"
    buckets (round-4 verdict, weak #3).  This version:

    - finds every 4-D shape in the text, takes the batch dim as the
      leading dim of the largest shape by element count (the streamed
      activation; a modal-leading-dim rule fails on wgrad fusions that
      fold the optimizer update and so mention the kernel shape 4x),
    - buckets by the batch-led shape with the largest spatial extent
      (the activation actually streamed from HBM), labelled HxWxC,
    - classifies the pass: ``wgrad`` when the op's *output* contains a
      4-D shape that is NOT batch-led (the kernel gradient), ``dgrad``
      when the JAX source path shows ``transpose(`` (reverse-mode),
      else ``fprop``.

    Returns ``"HxWxC:kind"`` so the bucket table still sums to the conv
    category total, or ``"other"`` when no 4-D shape appears.
    """
    shapes = [tuple(int(g) for g in m.groups())
              for m in _SHAPE_RE.finditer(hlo_text)]
    if not shapes:
        return "other"
    batch = max(shapes, key=lambda s: s[0] * s[1] * s[2] * s[3])[0]
    acts = [s for s in shapes if s[0] == batch]
    if acts:
        n, h, w, c = max(acts, key=lambda s: (s[1] * s[2], s[3]))
    else:
        n, h, w, c = shapes[0]
    out_part = hlo_output_part(hlo_text)
    out_shapes = [tuple(int(g) for g in m.groups())
                  for m in _SHAPE_RE.finditer(out_part)]
    if out_shapes and all(s[0] != batch for s in out_shapes):
        kind = "wgrad"
    elif "transpose(" in tf_op:
        kind = "dgrad"
    else:
        kind = "fprop"
    return f"{h}x{w}x{c}:{kind}"


def aggregate(events: list[dict], n_steps: int) -> dict:
    """events: [{name, display, category, dur_ps, flops, bytes,
    tf_op?}] over ``n_steps`` captured steps.  Returns {categories,
    conv_buckets, top_ops, totals} with per-STEP ms and measured rates.
    Rows shorter than ``SUB_RESOLUTION_MS`` per step carry
    ``rates_unreliable: true`` and suppressed (0.0) rates."""
    cats = defaultdict(lambda: [0, 0, 0, 0])       # dur, flops, bytes, n
    convs = defaultdict(lambda: [0, 0, 0, 0])
    ops = defaultdict(lambda: [0, 0, 0, 0, ""])
    for e in events:
        for table, key in ((cats, e["category"]),
                           (ops, e["display"])):
            a = table[key]
            a[0] += e["dur_ps"]
            a[1] += e["flops"]
            a[2] += e["bytes"]
            a[3] += 1
            if table is ops:
                a[4] = e["category"]
        if e["category"] == "convolution fusion":
            a = convs[conv_spatial_bucket(e["name"], e.get("tf_op", ""))]
            a[0] += e["dur_ps"]
            a[1] += e["flops"]
            a[2] += e["bytes"]
            a[3] += 1

    def row(d, f, b, n, *extra):
        ms = d / 1e9 / n_steps
        sec = d / 1e12
        unreliable = ms < SUB_RESOLUTION_MS
        return {
            "ms_per_step": round(ms, 3),
            "tflops_per_s": (round(f / sec / 1e12, 1)
                             if d and not unreliable else 0.0),
            "gbytes_per_s": (round(b / sec / 1e9, 1)
                             if d and not unreliable else 0.0),
            "events_per_step": n // n_steps,
            **({"rates_unreliable": True} if unreliable else {}),
            **({"category": extra[0]} if extra else {}),
        }

    total_dur = sum(v[0] for v in cats.values())
    total_flops = sum(v[1] for v in cats.values())
    return {
        "totals": {
            "device_busy_ms_per_step": round(total_dur / 1e9 / n_steps, 3),
            "achieved_tflops_per_s": round(
                total_flops / (total_dur / 1e12) / 1e12, 1)
            if total_dur else 0.0,
            "n_steps": n_steps,
        },
        "categories": {
            k: {**row(*v), "pct": round(100 * v[0] / total_dur, 1)}
            for k, v in sorted(cats.items(), key=lambda kv: -kv[1][0])
        },
        "conv_buckets": {
            k: {**row(*v), "pct": round(100 * v[0] / total_dur, 1)}
            for k, v in sorted(convs.items(), key=lambda kv: -kv[1][0])
        },
        "top_ops": [
            {"op": k, **row(*v[:4], v[4]),
             "pct": round(100 * v[0] / total_dur, 1)}
            for k, v in sorted(ops.items(), key=lambda kv: -kv[1][0])[:25]
        ],
    }


def roofline(report: dict, peak_tflops: float, peak_hbm_gbps: float) -> dict:
    """Per-slice roofline adjudication: a slice running at X TF/s while
    streaming Y GB/s has an HBM-implied ceiling of
    X * (peak_hbm / Y) — if that ceiling is close to X, the slice is
    bandwidth-bound and X is ~its achievable rate at this arithmetic
    intensity."""
    out = {}
    for k, c in report["categories"].items():
        if c.get("rates_unreliable"):
            out[k] = {"hbm_fraction": None, "mxu_fraction": None,
                      "hbm_implied_tflops_ceiling": None,
                      "rates_unreliable": True}
            continue
        gbs, tfs = c["gbytes_per_s"], c["tflops_per_s"]
        hbm_frac = gbs / peak_hbm_gbps if peak_hbm_gbps else 0.0
        implied = tfs / hbm_frac if hbm_frac > 0 else float("inf")
        entry = {
            "hbm_fraction": round(hbm_frac, 3),
            "mxu_fraction": round(tfs / peak_tflops, 3)
            if peak_tflops else 0.0,
            "hbm_implied_tflops_ceiling": (round(implied, 1)
                                           if implied != float("inf")
                                           else None),
        }
        # bytes_accessed counts every operand touch, including
        # VMEM-resident re-reads and async waits charged against tiny
        # on-stream durations — a "fraction" well past peak is an
        # accounting artifact, not a measurement of HBM streaming.
        if hbm_frac > 1.25:
            entry["accounting_artifact"] = True
            entry["hbm_implied_tflops_ceiling"] = None
        out[k] = entry
    return out


def pick_n_steps(line_event_counts: dict) -> int:
    """Number of captured steps from a {line_name: n_events} map.

    Prefers the 'XLA Modules' line (one event per module execution);
    falls back to 'Steps'; with neither, warns and returns 1 so the
    per-step columns are at least labelled honestly as per-capture.
    (Round-4 advisor: the old truthiness one-liner silently collapsed
    both absent and empty to 1 with no warning.)
    """
    n = line_event_counts.get("XLA Modules", 0)
    if n:
        return n
    n = line_event_counts.get("Steps", 0)
    if n:
        return n
    print("WARNING: no 'XLA Modules'/'Steps' line in this capture — "
          "treating the whole capture as ONE step; per-step columns "
          "are really per-capture", file=sys.stderr)
    return 1


# -- proto extraction -----------------------------------------------------

def _load_xspace(path: str):
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError as e:  # pragma: no cover
        raise SystemExit(
            "needs tensorflow's tsl xplane proto bindings "
            f"(import failed: {e}); on a box without tensorflow, copy "
            "the .xplane.pb to one that has it") from e
    space = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        space.ParseFromString(f.read())
    return space


def extract_device_events(space) -> tuple[list[dict], int, dict]:
    """(events, n_steps, device_info) from the first TPU/GPU device
    plane.  Events come from the 'XLA Ops' line; n_steps from the
    'XLA Modules' line (module executions captured)."""
    plane = None
    for p in space.planes:
        if "/device:" in p.name and "CUSTOM" not in p.name and any(
                ln.events for ln in p.lines):
            plane = p
            break
    if plane is None:
        raise SystemExit(
            "no device plane with events in this xplane — the capture "
            "has host threads only (the round-3 failure mode); re-trace "
            "with the step running on the device backend")
    sm, em = plane.stat_metadata, plane.event_metadata

    def stat_val(s):
        return (s.str_value or s.int64_value or s.uint64_value
                or s.double_value)

    info = {"plane": plane.name}
    for s in plane.stats:
        n = sm[s.metadata_id].name
        if n in ("device_type_string", "peak_teraflops_per_second",
                 "peak_hbm_bw_gigabytes_per_second"):
            info[n] = stat_val(s)

    lines = {ln.name: ln for ln in plane.lines}
    n_steps = pick_n_steps({ln.name: len(ln.events) for ln in plane.lines})
    events = []
    for e in lines["XLA Ops"].events:
        md = em[e.metadata_id]
        st = {sm[s.metadata_id].name: stat_val(s) for s in md.stats}
        events.append({
            "name": md.name,
            "display": md.display_name,
            "category": st.get("hlo_category", "?"),
            "dur_ps": e.duration_ps,
            "flops": st.get("flops", 0) or 0,
            "bytes": st.get("bytes_accessed", 0) or 0,
            "tf_op": st.get("tf_op", "") or "",
        })
    return events, n_steps, info


def find_xplane(path: str) -> str:
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"),
                            recursive=True))
    if not hits:
        raise SystemExit(f"no *.xplane.pb under {path}")
    return hits[-1]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("path", help="profile dir or .xplane.pb file")
    ap.add_argument("--out", default=None, help="write full JSON here")
    ap.add_argument("--copies", action="store_true",
                    help="attribute every copy-start/done event to its "
                         "producer (direction x size-class x shape)")
    args = ap.parse_args()

    pb = find_xplane(args.path)
    events, n_steps, info = extract_device_events(_load_xspace(pb))
    report = aggregate(events, n_steps)
    peak_tf = float(info.get("peak_teraflops_per_second", 0) or 0)
    peak_bw = float(info.get("peak_hbm_bw_gigabytes_per_second", 0) or 0)
    rl = roofline(report, peak_tf, peak_bw)

    t = report["totals"]
    print(f"# {info.get('device_type_string', '?')} — peak "
          f"{peak_tf:.0f} TF/s, HBM {peak_bw:.0f} GB/s ({info['plane']})")
    print(f"# {t['n_steps']} steps captured, device-busy "
          f"{t['device_busy_ms_per_step']} ms/step, achieved "
          f"{t['achieved_tflops_per_s']} TF/s over device-busy time")
    print(f"{'category':<26}{'ms/step':>9}{'%':>7}{'TF/s':>8}{'GB/s':>8}"
          f"{'%HBM':>7}{'ceilTF/s':>10}")
    for k, c in report["categories"].items():
        r = rl[k]
        ceil = r["hbm_implied_tflops_ceiling"]
        if c.get("rates_unreliable"):
            print(f"{k[:25]:<26}{c['ms_per_step']:9.3f}{c['pct']:7.1f}"
                  f"{'(sub-resolution: rates suppressed)':>40}")
            continue
        frac = r["hbm_fraction"] or 0.0
        art = "*" if r.get("accounting_artifact") else ""
        print(f"{k[:25]:<26}{c['ms_per_step']:9.3f}{c['pct']:7.1f}"
              f"{c['tflops_per_s']:8.1f}{c['gbytes_per_s']:8.0f}"
              f"{100 * frac:6.1f}{art:1}"
              f"{(f'{ceil:10.1f}' if ceil else '         -')}")
    print(f"\n{'conv bucket (HxWxC:kind)':<26}{'ms/step':>9}{'%':>7}"
          f"{'TF/s':>8}{'GB/s':>8}")
    for k, c in report["conv_buckets"].items():
        print(f"{k:<26}{c['ms_per_step']:9.3f}{c['pct']:7.1f}"
              f"{c['tflops_per_s']:8.1f}{c['gbytes_per_s']:8.0f}")
    copies = None
    if args.copies:
        copies = attribute_copies(events, n_steps)
        print(f"\n== copy attribution: copy-done "
              f"{copies['copy_done_ms_per_step']} ms/step over "
              f"{copies['copy_done_events_per_step']} events (+ "
              f"copy-start {copies['copy_start_ms_per_step']} ms) ==")
        print(f"{'ms/step':>8}{'n':>6}{'us/ea':>7}{'MB/step':>9}"
              f"{'%copy':>7}  producer")
        for r in copies["rows"][:20]:
            print(f"{r['ms_per_step']:8.3f}{r['events_per_step']:6d}"
                  f"{r['us_per_event']:7.2f}{r['mbytes_per_step']:9.1f}"
                  f"{r['pct_of_copy_done']:7.1f}  {r['producer']}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"device": info, "report": report, "roofline": rl,
                       **({"copy_attribution": copies} if copies
                          else {}),
                       "source": pb}, f, indent=1)
        print(f"\nwrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
