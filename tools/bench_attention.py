"""Microbench: XLA-composed vs Pallas fused attention on the chip.

Decides (and re-validates) ops/attention.py's 'auto' = Pallas-on-TPU
default; run with no env overrides to hit the real TPU.  Benches the
causal fwd and fwd+bwd at transformer-shaped sizes.

Usage: python tools/bench_attention.py [batch] [seqlen]
"""

from __future__ import annotations

import os
import sys
import time

# NOTE: do NOT use PYTHONPATH for this — setting it can break the axon
# TPU plugin's sitecustomize registration in this environment
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _bootstrap  # noqa: F401  (makes JAX_PLATFORMS effective)
import jax
import jax.numpy as jnp

from theanompi_tpu.ops.attention import fused_attention


def bench(fn, args, n_iters=30):
    y = fn(*args)
    jax.block_until_ready(y)
    float(jax.tree.leaves(y)[0].ravel()[0])  # readback fence
    t0 = time.perf_counter()
    for _ in range(n_iters):
        y = fn(*args)
    float(jax.tree.leaves(y)[0].ravel()[0])
    return (time.perf_counter() - t0) / n_iters * 1e3


def main():
    b = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    t = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    h, d = 8, 64
    print(f"backend={jax.default_backend()} shape=({b},{t},{h},{d}) bf16")
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (b, t, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, t, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, t, h, d), jnp.bfloat16)

    for impl in ("xla", "pallas"):
        fwd = jax.jit(lambda q, k, v, impl=impl: fused_attention(
            q, k, v, causal=True, impl=impl))
        ms = bench(fwd, (q, k, v))
        print(f"{impl:7s} fwd     {ms:8.3f} ms")

        grad = jax.jit(jax.grad(lambda q, k, v, impl=impl: fused_attention(
            q, k, v, causal=True, impl=impl).astype(jnp.float32).sum(),
            argnums=(0, 1, 2)))
        ms = bench(grad, (q, k, v))
        print(f"{impl:7s} fwd+bwd {ms:8.3f} ms")


if __name__ == "__main__":
    main()
