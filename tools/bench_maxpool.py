"""Microbench: XLA vs Pallas stem max-pool fwd+bwd on the chip.

The MFU account charges the XLA maxpool backward (select-and-scatter)
0.761 ms/step at 608 GB/s = 74% of HBM peak — the only near-zero-FLOP
slice with bandwidth headroom.  The Pallas kernel
(ops/maxpool_pallas.py) saves the window argmax at forward time and
computes the backward as a gather (~282 vs ~460 MB), predicting
~0.34 ms.  This measures both at the flagship shape and prints one
JSON line per impl; if pallas wins fwd+bwd, set
``ModelConfig.pool_impl='pallas'`` (and flip the recipe defaults).

Usage:
    python tools/bench_maxpool.py [batch] [hw] [channels]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _bootstrap  # noqa: F401,E402  (makes JAX_PLATFORMS effective)
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from theanompi_tpu.ops.maxpool import maxpool_stem  # noqa: E402


def bench(fn, x, n_iters=30):
    g = jax.jit(jax.grad(lambda x: (fn(x).astype(jnp.float32) ** 2).sum()))
    y = g(x)
    jax.block_until_ready(y)
    float(jnp.asarray(y).ravel()[0])  # readback fence (axon)
    t0 = time.perf_counter()
    for _ in range(n_iters):
        y = g(x)
    float(jnp.asarray(y).ravel()[0])
    return (time.perf_counter() - t0) / n_iters * 1e3


def main() -> int:
    # the env var overrides maxpool_stem's impl argument BY DESIGN (the
    # recipe A/B knob) — which would make this A/B bench measure one
    # impl twice under two labels; drop it for the comparison
    if os.environ.pop("THEANOMPI_TPU_POOL_IMPL", None):
        print("# ignoring THEANOMPI_TPU_POOL_IMPL for the A/B bench",
              file=sys.stderr)
    b = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    hw = int(sys.argv[2]) if len(sys.argv) > 2 else 112
    c = int(sys.argv[3]) if len(sys.argv) > 3 else 64
    x = jax.random.normal(jax.random.key(0), (b, hw, hw, c),
                          jnp.bfloat16)
    results = {}
    for impl in ("xla", "pallas"):
        ms = bench(lambda x, i=impl: maxpool_stem(x, impl=i), x)
        results[impl] = ms
        print(json.dumps({
            "exp": "maxpool_stem", "impl": impl,
            "shape": [b, hw, hw, c], "dtype": "bfloat16",
            "fwd_bwd_ms": round(ms, 3),
            "backend": jax.default_backend(),
        }), flush=True)
    print(json.dumps({
        "exp": "maxpool_stem", "event": "summary",
        "speedup_pallas": round(results["xla"] / results["pallas"], 3),
    }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
