"""Turn the on-chip experiment queue's JSONL into a decision table.

The TPU tunnel in this environment serves in rare windows, so all
on-chip experiments run from a sequential queue that appends one JSON
line per result (BASELINE.md "Round-2 on-chip caveat" explains the
wedge cycle).  This tool ingests that log and prints:

* a markdown table of every ResNet ladder point (k x batch x stem)
  with img/s/chip and achieved TF/s (2xMAC, 24.6 GF/img trained),
* the winning configuration and the env defaults to adopt in bench.py
  (``THEANOMPI_TPU_BENCH_K`` / ``_BATCH`` and ``resnet_stem``),
* any attention / h2d / conv-ladder summary lines found.

Usage:
    python tools/harvest_queue.py /tmp/tpu_queue.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from flop_constants import TRAIN_GFLOP_PER_IMAGE as TRAIN_GF_PER_IMG  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("log", help="queue JSONL (one result object per line)")
    args = ap.parse_args()

    rows, attn, h2d, ladder, failed, misc = [], [], [], [], [], []
    with open(args.log) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            exp = rec.get("exp")
            # failure records carry the exp NAME plus error/tb — they
            # must land in the failed section, never in a success table
            if "error" in rec or "tb" in rec:
                failed.append(rec)
            elif exp == "resnet50" and "img_per_sec_per_chip" in rec:
                rows.append(rec)
            elif exp == "attention":
                attn.append(rec)
            elif exp == "h2d":
                h2d.append(rec)
            elif rec.get("event") == "ladder_summary" or exp == "conv_ladder":
                ladder.append(rec)
            else:
                misc.append(rec)  # start/done/profile/per-shape rows —
                # shown verbatim so nothing the queue did goes unreported

    if not rows:
        print("no ResNet ladder points in the log (tunnel never served?)",
              file=sys.stderr)

    if rows:
        print("| k | batch/chip | stem | xla flags | img/s/chip "
              "| TF/s (2xMAC) | dispatch ms | compile s |")
        print("|---|---|---|---|---|---|---|---|")
        for r in rows:
            tfs = r["img_per_sec_per_chip"] * TRAIN_GF_PER_IMG / 1e3
            # the r5 queue sweeps --xla_tpu_scoped_vmem_limit_kib;
            # show the flag so sweep rows are distinguishable from the
            # default-flag ladder
            flags = r.get("xla_flags", "") or "-"
            flags = flags.replace("--xla_tpu_scoped_vmem_limit_kib=",
                                  "vmem_kib=")
            print(f"| {r['steps_per_call']} | {r['batch_per_chip']} "
                  f"| {r.get('stem', 'conv7')} | {flags} "
                  f"| {r['img_per_sec_per_chip']} | {tfs:.1f} "
                  f"| {r['dispatch_ms']} | {r.get('compile_s', '?')} |")
        best = max(rows, key=lambda r: r["img_per_sec_per_chip"])
        bflags = best.get("xla_flags", "") or ""
        print(f"\nwinner: k={best['steps_per_call']} "
              f"b={best['batch_per_chip']} stem={best.get('stem', 'conv7')}"
              + (f" xla_flags={bflags}" if bflags else "")
              + f" -> {best['img_per_sec_per_chip']} img/s/chip")
        print("adopt in bench.py defaults: "
              f"THEANOMPI_TPU_BENCH_K={best['steps_per_call']} "
              f"THEANOMPI_TPU_BENCH_BATCH={best['batch_per_chip']}"
              + ("" if best.get("stem", "conv7") == "conv7"
                 else "  (+ ModelConfig resnet_stem='s2d')")
              + ("" if not bflags
                 else f"  (+ XLA_FLAGS+=' {bflags}' — a sweep row won; "
                      "bench.py cannot reproduce it without the flag)"))

    for name, items in (("attention", attn), ("h2d", h2d),
                        ("conv ladder", ladder),
                        ("other records (start/done/profile/...)", misc)):
        if items:
            print(f"\n-- {name} --")
            for r in items:
                print(json.dumps(r))
    if failed:
        print(f"\n-- {len(failed)} failed experiment(s) --")
        for r in failed:
            print(json.dumps(r)[:300])
    # nonzero when there is nothing to adopt defaults from, so an
    # automated harvest-then-adopt flow can detect a never-served tunnel
    return 0 if rows else 1


if __name__ == "__main__":
    raise SystemExit(main())
