"""Distributed-ingest benchmark — a REAL N-reader fleet over sockets
(ISSUE 9 measurement leg).

Drives T trainer streams (``RemoteBatchSource``, the exact client the
rules use) against an :class:`IngestProcessGroup` of N real reader
processes serving a real mmap shard tree, and reports the aggregate
delivered rate per fleet size.  The N=1 vs N=2 comparison consumes the
IDENTICAL batch set (same dataset, same epoch permutation, same
trainer count — the streams are byte-identical by construction, and
the bench cross-checks the consumed byte totals), so the ratio
isolates what the fleet adds: assembly + framing CPU moving out of one
process into N.

``--smoke`` is the preflight gate (exit 1 on any miss):

* N=2 aggregate img/s >= ``--scale-bar`` (default 1.7) x N=1 at
  identical total bytes;
* the kill leg — one reader is SIGKILLed mid-epoch; the client fails
  over (stream completes, byte-identical count), the fleet watcher
  relaunches the corpse — and the recovery counters
  (``ingest/reader_failovers_total``, ``ingest/reader_restarts_total``)
  land in the monitor JSONL;
* every reader actually served traffic (per-reader ``ingest_pull``
  spans in the monitor JSONL).

Usage:
    python tools/bench_ingest.py                    # full, ~16k samples
    python tools/bench_ingest.py --smoke            # preflight gate
    python tools/bench_ingest.py --readers 4 --trainers 4
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import _bootstrap  # noqa: F401,E402  (tools/ sibling; pins JAX_PLATFORMS)

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def build_tree(n_samples: int, store: int, shard_size: int,
               seed: int = 0) -> str:
    """A real shard tree of random uint8 images in a temp dir."""
    from theanompi_tpu.data.imagenet import prepare_imagenet_shards

    d = tempfile.mkdtemp(prefix="bench_ingest_")
    rng = np.random.default_rng(seed)
    # write in slabs so the bench never holds the whole set in RAM
    slab = max(shard_size, 2048)
    offset = 0
    while offset < n_samples:
        n = min(slab, n_samples - offset)
        imgs = rng.integers(0, 255, size=(n, store, store, 3),
                            dtype=np.uint8)
        labels = rng.integers(0, 1000, size=n).astype(np.int64)
        prepare_imagenet_shards(
            imgs, labels, d, prefix=f"train_{offset:07d}",
            shard_size=shard_size)
        offset += n
    return d


def trainer_worker(args) -> int:
    """``--worker`` mode: ONE trainer process driving one epoch
    stream — real trainers are separate processes (each owns its GIL
    and its pipelined fetch loop), so the parent measures the fleet,
    not a single client process's ceiling.  Protocol: warm pass,
    print READY, wait for GO on stdin (so all workers' timed windows
    overlap), timed pass, print one JSON line."""
    from theanompi_tpu.data.imagenet import ImageNet_data
    from theanompi_tpu.ingest.client import RemoteBatchSource

    if os.environ.get("THEANOMPI_TPU_INGEST_DEBUG_DUMP"):
        import faulthandler

        faulthandler.dump_traceback_later(
            float(os.environ["THEANOMPI_TPU_INGEST_DEBUG_DUMP"]),
            exit=True)
    ds = ImageNet_data(data_dir=args.data_dir, crop=args.store, seed=0,
                       augment_on_device=True)
    addrs = args.worker_addrs.split(",")

    def one_pass():
        n = imgs = nbytes = 0
        t0 = time.monotonic()
        with RemoteBatchSource(addrs, data=ds, epoch=0,
                               global_batch=args.batch,
                               rank=args.worker_rank,
                               size=args.worker_size,
                               depth=args.depth) as src:
            for x, y in src:
                n += 1
                imgs += len(y)
                nbytes += x.nbytes + y.nbytes
        return {"batches": n, "images": imgs, "bytes": nbytes,
                "wall_s": time.monotonic() - t0}

    one_pass()  # warm: page cache + codepaths
    print("READY", flush=True)
    if sys.stdin.readline().strip() != "GO":
        return 1
    print(json.dumps(one_pass()), flush=True)
    return 0


def drive_trainers(addrs: list[str], data_dir: str, t_count: int,
                   batch: int, store: int, depth: int) -> dict:
    """T trainer PROCESSES consuming their epoch streams concurrently
    (ready/go barrier so the timed windows overlap); aggregate img/s
    = total images / the longest worker wall.  The per-stream byte
    totals double as the identical-bytes cross-check between fleet
    sizes."""
    import subprocess

    procs = []
    for t in range(t_count):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--worker-rank", str(t), "--worker-size", str(t_count),
               "--worker-addrs", ",".join(addrs),
               "--data-dir", data_dir, "--batch", str(batch),
               "--store", str(store), "--depth", str(depth)]
        procs.append(subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, env=dict(os.environ)))
    try:
        for p in procs:
            line = p.stdout.readline().strip()
            if line != "READY":
                raise RuntimeError(
                    f"trainer worker failed before READY: {line!r} "
                    f"(rc={p.poll()})")
        for p in procs:
            p.stdin.write("GO\n")
            p.stdin.flush()
        outs = []
        for p in procs:
            outs.append(json.loads(p.stdout.readline()))
            p.stdin.close()
        for p in procs:
            p.wait(timeout=60)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    wall = max(o["wall_s"] for o in outs)
    return {"wall_s": round(wall, 3),
            "batches": sum(o["batches"] for o in outs),
            "images": sum(o["images"] for o in outs),
            "bytes": sum(o["bytes"] for o in outs),
            "agg_img_s": round(sum(o["images"] for o in outs) / wall,
                               1)}


def shm_compare_leg(samples: int = 8192, store: int = 96,
                    shard_size: int = 512, batch: int = 256,
                    depth: int = 4) -> dict:
    """Ingest plane of the shared-memory-lane comparison (ISSUE 20):
    in-band wire v2 vs the shm lane over the SAME committed workload —
    identical shard tree, epoch permutation and batch schedule, so the
    delivered streams are sha256-checked byte-identical across legs.
    Each leg gets a FRESH reader process (no negotiated lane state
    leaks between legs); the parent consumes the stream directly so
    the client-side lane counters land in the caller's monitor
    session, which the caller owns (``monitor.registry()`` is
    process-global).  Returns the ingest plane doc for
    ``BENCH_shm_smoke.json``."""
    import hashlib

    from theanompi_tpu import monitor
    from theanompi_tpu.data.imagenet import ImageNet_data
    from theanompi_tpu.ingest.client import RemoteBatchSource
    from theanompi_tpu.ingest.fleet import IngestProcessGroup
    from theanompi_tpu.parallel import shm

    data_dir = build_tree(samples, store, shard_size)
    pre_segments = set(shm.segment_names())
    prior = os.environ.get("THEANOMPI_TPU_WIRE_SHM")
    reg = monitor.registry()
    val = lambda name, **lb: reg.value(name, **lb) or 0.0
    legs: dict[str, dict] = {}
    try:
        dataset = ImageNet_data(data_dir=data_dir, crop=store, seed=0,
                                augment_on_device=True)

        def hash_pass(addrs: list[str]) -> str:
            """Warm pass doubling as the identity proof: sha256 over
            every delivered byte — the same epoch-1 stream the timed
            pass re-consumes (identical permutation + schedule)."""
            digest = hashlib.sha256()
            with RemoteBatchSource(addrs, data=dataset, epoch=1,
                                   global_batch=batch,
                                   depth=depth) as src:
                for x, y in src:
                    digest.update(x.tobytes())
                    digest.update(y.tobytes())
            return digest.hexdigest()

        def timed_pass(addrs: list[str]) -> dict:
            """Throughput pass: every byte is still READ (a training
            step consumes the whole batch) via a cheap reduction, but
            no cryptographic hash dilutes the transport difference —
            the sums double as a secondary cross-leg identity check."""
            images = nbytes = batches = 0
            checksum = 0
            t0 = time.monotonic()
            with RemoteBatchSource(addrs, data=dataset, epoch=1,
                                   global_batch=batch,
                                   depth=depth) as src:
                for x, y in src:
                    checksum += int(x.sum(dtype=np.int64))
                    checksum += int(y.sum(dtype=np.int64))
                    batches += 1
                    images += len(y)
                    nbytes += x.nbytes + y.nbytes
            wall = time.monotonic() - t0
            return {"wall_s": round(wall, 3), "batches": batches,
                    "images": images, "bytes": nbytes,
                    "img_s": round(images / wall, 1),
                    "checksum": checksum}

        for name, lane in (("in_band", "0"), ("shm", "1")):
            # the reader subprocess inherits the toggle; the parent
            # client reads it at hello time — both sides of the leg
            # negotiate (or never offer) the lane consistently
            os.environ["THEANOMPI_TPU_WIRE_SHM"] = lane
            oob0 = val("shm/oob_bytes_total", dir="recv")
            grants0 = val("shm/grants_total", role="client")
            group = IngestProcessGroup(1, data_dir, seed=0,
                                       coordinator=False,
                                       max_restarts=1)
            try:
                addrs = group.reader_addresses
                sha = hash_pass(addrs)  # warm + identity evidence
                r = timed_pass(addrs)
                r["sha256"] = sha
            finally:
                group.stop()
            r["oob_bytes_recv"] = int(
                val("shm/oob_bytes_total", dir="recv") - oob0)
            r["shm_grants"] = int(
                val("shm/grants_total", role="client") - grants0)
            legs[name] = r
            print(f"[bench_ingest] shm-compare {name}: "
                  f"{r['img_s']:.0f} img/s, "
                  f"{r['oob_bytes_recv']/1e6:.1f} MB out-of-band",
                  flush=True)
    finally:
        if prior is None:
            os.environ.pop("THEANOMPI_TPU_WIRE_SHM", None)
        else:
            os.environ["THEANOMPI_TPU_WIRE_SHM"] = prior
        shutil.rmtree(data_dir, ignore_errors=True)
    shm.sweep_orphans()
    leaked = [n for n in shm.segment_names() if n not in pre_segments]
    ratio = legs["shm"]["img_s"] / legs["in_band"]["img_s"]
    return {
        "plane": "ingest",
        "samples": samples, "store_px": store, "batch": batch,
        "depth": depth,
        "legs": legs,
        "img_s_ratio_shm_over_in_band": round(ratio, 3),
        "byte_identical": (legs["shm"]["sha256"]
                           == legs["in_band"]["sha256"]
                           and legs["shm"]["checksum"]
                           == legs["in_band"]["checksum"]),
        # payload bytes that left the socket path entirely (the
        # receiver maps them instead of copying them off the wire)
        "socket_bytes_saved": legs["shm"]["oob_bytes_recv"],
        "leaked_segments": len(leaked),
    }


def shm_evidence(monitor_dir: str | None, since: float = 0.0) -> dict:
    """Scan every metrics JSONL in ``monitor_dir`` written after
    ``since`` for shared-memory-lane evidence.  Subprocess roles
    (readers, shards, prefill/decode replicas) run their OWN monitor
    sessions writing sibling ``metrics_*.jsonl`` files into the shared
    dir, so the parent's snapshot alone never shows the server side of
    the lane — this aggregates both sides.  Counter snapshots are
    cumulative, so per (file, name, labels) the LAST value wins."""
    grants = 0.0
    oob = 0.0
    if not monitor_dir or not os.path.isdir(monitor_dir):
        return {"grants": 0, "oob_bytes": 0}
    for fn in sorted(os.listdir(monitor_dir)):
        path = os.path.join(monitor_dir, fn)
        if not fn.endswith(".jsonl"):
            continue
        try:
            if os.path.getmtime(path) < since:
                continue
            last: dict[str, float] = {}
            with open(path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    name = rec.get("name")
                    if name in ("shm/grants_total",
                                "shm/oob_bytes_total"):
                        key = f"{name}|{sorted((rec.get('labels') or {}).items())}"
                        last[key] = float(rec.get("value") or 0.0)
            for key, v in last.items():
                if key.startswith("shm/grants_total"):
                    grants += v
                else:
                    oob += v
        except OSError:
            continue
    return {"grants": int(grants), "oob_bytes": int(oob)}


def run_shm_compare(args) -> int:
    """``--shm-compare`` mode: the standalone ingest shm leg —
    in-band vs lane over the identical stream, fresh reader process
    per leg; with ``--smoke`` asserts the >= ``--shm-bar`` img/s
    lift, byte identity, lane evidence, and zero leaked segments."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    os.environ.setdefault("THEANOMPI_TPU_SERVICE_KEY", "bench-ingest")
    os.environ.setdefault(
        "THEANOMPI_TPU_MONITOR",
        os.path.join(REPO, "artifacts", "bench_ingest_monitor"))

    from theanompi_tpu import monitor

    n_samples = args.samples or (8192 if args.smoke else 16384)
    # the lane targets payload-dominated batches (pixels >> skeleton);
    # the default 64-image batch is a latency workload, not this one
    batch = max(args.batch, 256)
    with monitor.session():
        doc = shm_compare_leg(n_samples, args.store, args.shard_size,
                              batch, args.depth)
    out_doc = {"bench": "ingest_shm_lane", "backend": "cpu", **doc}
    tag = args.tag or "ingest_shm"
    path = args.out or os.path.join(REPO, "artifacts",
                                    f"BENCH_{tag}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out_doc, f, indent=1)
    print(f"[bench_ingest] wrote {path} (shm "
          f"{doc['img_s_ratio_shm_over_in_band']:.2f}x in-band img/s)",
          flush=True)
    if not args.smoke:
        return 0
    ok = True
    if not doc["byte_identical"]:
        print("[bench_ingest] FAIL: shm leg delivered different bytes "
              "than the in-band leg", file=sys.stderr)
        ok = False
    if doc["img_s_ratio_shm_over_in_band"] < args.shm_bar:
        print(f"[bench_ingest] FAIL: shm img/s "
              f"{doc['img_s_ratio_shm_over_in_band']:.2f}x in-band < "
              f"{args.shm_bar}x bar", file=sys.stderr)
        ok = False
    if doc["legs"]["shm"]["oob_bytes_recv"] <= 0 \
            or doc["legs"]["shm"]["shm_grants"] < 1:
        print("[bench_ingest] FAIL: shm leg shows no lane traffic "
              f"({doc['legs']['shm']})", file=sys.stderr)
        ok = False
    if doc["legs"]["in_band"]["oob_bytes_recv"] != 0:
        print("[bench_ingest] FAIL: in-band leg leaked lane traffic "
              f"({doc['legs']['in_band']})", file=sys.stderr)
        ok = False
    if doc["leaked_segments"]:
        print(f"[bench_ingest] FAIL: {doc['leaked_segments']} shm "
              "segment(s) leaked after the legs", file=sys.stderr)
        ok = False
    print(f"[bench_ingest] shm-compare {'PASS' if ok else 'FAIL'}",
          flush=True)
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--readers", type=int, default=2, metavar="N")
    ap.add_argument("--trainers", type=int, default=4, metavar="T",
                    help="trainer PROCESSES; demand must exceed one "
                         "reader's capacity or N=1 vs N=2 compares "
                         "two idle fleets")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--store", type=int, default=None,
                    help="stored image side (uint8 HxWx3); default 64, "
                         "96 under --shm-compare (payload-dominated "
                         "batches are the lane's target workload)")
    ap.add_argument("--samples", type=int, default=None,
                    help="dataset size (default 65536; 32768 in "
                         "--smoke)")
    ap.add_argument("--shard-size", type=int, default=512)
    ap.add_argument("--depth", type=int, default=6,
                    help="per-trainer pipelined pulls")
    ap.add_argument("--data-dir", default=None,
                    help="existing shard tree (default: build a "
                         "synthetic one in a temp dir)")
    ap.add_argument("--scale-bar", type=float, default=1.7,
                    help="--smoke: required N=2/N=1 aggregate ratio")
    ap.add_argument("--out", default=None)
    ap.add_argument("--tag", default=None)
    ap.add_argument("--shm-compare", action="store_true",
                    help="shared-memory-lane leg (ISSUE 20): in-band "
                         "vs shm over the identical stream, one fresh "
                         "reader process per leg, sha256 byte-identity "
                         "checked; with --smoke asserts the --shm-bar "
                         "img/s lift + lane evidence + zero leaked "
                         "segments")
    ap.add_argument("--shm-bar", type=float, default=1.3,
                    help="--shm-compare --smoke: required shm/in-band "
                         "aggregate img/s ratio")
    ap.add_argument("--smoke", action="store_true",
                    help="preflight gate: assert the scaling bar, the "
                         "kill-recovery leg, and the monitor evidence; "
                         "exit 1 on any miss")
    # internal: one trainer process of drive_trainers' barrier fleet
    ap.add_argument("--worker-rank", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--worker-size", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--worker-addrs", default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.store is None:
        args.store = 96 if args.shm_compare else 64
    if args.worker_rank is not None:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return trainer_worker(args)
    if args.shm_compare:
        return run_shm_compare(args)

    # ingest is a host-plane bench: numpy + sockets; keep jax off any
    # real accelerator in every process of the fleet
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    os.environ.setdefault("THEANOMPI_TPU_SERVICE_KEY", "bench-ingest")
    os.environ.setdefault(
        "THEANOMPI_TPU_MONITOR",
        os.path.join(REPO, "artifacts", "bench_ingest_monitor"))

    from theanompi_tpu import monitor
    from theanompi_tpu.data.imagenet import ImageNet_data
    from theanompi_tpu.ingest.fleet import IngestProcessGroup

    n_samples = args.samples or (32768 if args.smoke else 65536)
    own_tree = args.data_dir is None
    data_dir = args.data_dir or build_tree(n_samples, args.store,
                                           args.shard_size)
    dataset = ImageNet_data(data_dir=data_dir, crop=args.store,
                            seed=0, augment_on_device=True)
    print(f"[bench_ingest] tree: {dataset.n_train} samples x "
          f"{args.store}px uint8, {len(dataset.train_files)} "
          f"files; {args.trainers} trainer process(es), batch "
          f"{args.batch}, depth {args.depth}", flush=True)

    modes = []
    kill = None
    t_start = time.time()
    try:
        with monitor.session():
            for n_readers in ([1, args.readers]
                              if args.readers > 1 else [1]):
                group = IngestProcessGroup(
                    n_readers, data_dir, seed=0, coordinator=False,
                    max_restarts=2)
                try:
                    addrs = group.reader_addresses
                    # workers warm their own pass before the barrier,
                    # so both fleet sizes measure warm page cache
                    r = drive_trainers(addrs, data_dir, args.trainers,
                                       args.batch, args.store,
                                       args.depth)
                    r["readers"] = n_readers
                    r["served_per_reader"] = reader_served(addrs)
                    modes.append(r)
                    print(f"[bench_ingest] N={n_readers}: "
                          f"{r['agg_img_s']:.0f} img/s aggregate, "
                          f"{r['bytes']/1e6:.1f} MB in "
                          f"{r['wall_s']:.2f}s", flush=True)
                    if args.smoke and n_readers > 1:
                        kill = kill_leg(group, dataset, args)
                finally:
                    group.stop()
            snapshot_path = monitor.flush()
    finally:
        if own_tree:
            shutil.rmtree(data_dir, ignore_errors=True)

    n1 = next(m for m in modes if m["readers"] == 1)
    nk = modes[-1]
    scaling = (nk["agg_img_s"] / n1["agg_img_s"]
               if nk is not n1 else 1.0)
    out_doc = {
        "bench": "ingest_fleet",
        "backend": "cpu",
        "n_samples": dataset.n_train,
        "store_px": args.store,
        "batch": args.batch,
        "trainers": args.trainers,
        "depth": args.depth,
        "modes": modes,
        "aggregate_scaling_vs_n1": round(scaling, 3),
        "identical_total_bytes": n1["bytes"] == nk["bytes"],
        "kill_leg": kill,
    }
    tag = args.tag or ("smoke" if args.smoke
                       else f"n{args.readers}t{args.trainers}")
    path = args.out or os.path.join(REPO, "artifacts",
                                    f"BENCH_ingest_{tag}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out_doc, f, indent=1)
    print(f"[bench_ingest] wrote {path} (N={nk['readers']} aggregate "
          f"{scaling:.2f}x N=1)", flush=True)

    if not args.smoke:
        return 0
    return smoke_verdict(out_doc, args, snapshot_path, since=t_start)


def reader_served(addrs: list[str]) -> list[int]:
    """Per-reader served-batch counters (the 'every reader actually
    served its range' evidence, straight from the reader processes)."""
    from theanompi_tpu.parallel.service import ServiceClient

    out = []
    for addr in addrs:
        c = ServiceClient(addr)
        try:
            out.append(int(c.call("stats")["served"]))
        finally:
            c.close()
    return out


def kill_leg(group, ds, args) -> dict:
    """Mid-epoch reader death: SIGKILL reader 0, the client stream
    must complete byte-identically over the survivor while the
    watcher relaunches the corpse."""
    from theanompi_tpu.ingest.client import RemoteBatchSource
    expected = ds.n_train_batches_for(1, args.batch, 0, 1)
    got = 0
    with RemoteBatchSource(group.reader_addresses, data=ds, epoch=1,
                           global_batch=args.batch, depth=args.depth
                           ) as src:
        it = iter(src)
        for _ in range(3):
            next(it)
            got += 1
        group.kill_reader(0)
        print("[bench_ingest] kill leg: reader 0 SIGKILLed mid-epoch",
              flush=True)
        for _ in it:
            got += 1
    group.wait_restarted(0)
    restarts = group.restart_counts()
    out = {"expected_batches": expected, "completed_batches": got,
           "reader0_restarts": restarts.get(0, 0),
           "recovered": got == expected and restarts.get(0, 0) >= 1}
    print(f"[bench_ingest] kill leg: {out}", flush=True)
    return out


def smoke_verdict(doc: dict, args, snapshot_path: str | None,
                  since: float = 0.0) -> int:
    ok = True
    if args.readers < 2:
        print("[bench_ingest] FAIL: smoke needs --readers >= 2",
              file=sys.stderr)
        ok = False
    if not doc["identical_total_bytes"]:
        print("[bench_ingest] FAIL: fleet sizes consumed different "
              "byte totals — the comparison is not like-for-like",
              file=sys.stderr)
        ok = False
    if doc["aggregate_scaling_vs_n1"] < args.scale_bar:
        print(f"[bench_ingest] FAIL: N={args.readers} aggregate "
              f"{doc['aggregate_scaling_vs_n1']:.2f}x N=1 < "
              f"{args.scale_bar}x bar", file=sys.stderr)
        ok = False
    if not (doc["kill_leg"] or {}).get("recovered"):
        print("[bench_ingest] FAIL: the kill-one-reader leg did not "
              "recover", file=sys.stderr)
        ok = False
    nk = doc["modes"][-1]
    if not all(s > 0 for s in nk.get("served_per_reader", [])):
        print(f"[bench_ingest] FAIL: a reader of the N="
              f"{nk['readers']} fleet served nothing "
              f"({nk.get('served_per_reader')})", file=sys.stderr)
        ok = False
    # monitor JSONL evidence: per-reader serving spans + the recovery
    # counters (the operator-facing proof, like the shard smoke's)
    served, names = set(), set()
    if snapshot_path and os.path.exists(snapshot_path):
        with open(snapshot_path) as f:
            for line in f:
                rec = json.loads(line)
                names.add(rec.get("name"))
                if (rec.get("name") == "span_ms"
                        and rec.get("labels", {}).get("name")
                        == "ingest_pull" and rec.get("count", 0) > 0):
                    served.add(rec["labels"].get("reader"))
    if len(served) < args.readers:
        print(f"[bench_ingest] FAIL: ingest_pull spans name only "
              f"{len(served)} reader(s) ({sorted(served)}) in the "
              f"monitor JSONL ({snapshot_path}); expected "
              f"{args.readers}", file=sys.stderr)
        ok = False
    for needed in ("ingest/reader_failovers_total",
                   "ingest/reader_restarts_total"):
        if needed not in names:
            print(f"[bench_ingest] FAIL: {needed} missing from the "
                  f"monitor JSONL ({snapshot_path})", file=sys.stderr)
            ok = False
    # shm-lane evidence (ISSUE 20): same-host readers must have
    # granted the lane and shipped batch pixels out-of-band.  The
    # trainer workers run no monitor session, so the proof lives in
    # the READER processes' sibling metrics files — scan the dir.
    from theanompi_tpu.parallel import shm

    if shm.enabled() and shm.available():
        mon_dir = os.path.dirname(snapshot_path) if snapshot_path \
            else os.environ.get("THEANOMPI_TPU_MONITOR")
        ev = shm_evidence(mon_dir, since=since)
        if ev["grants"] < 1 or ev["oob_bytes"] <= 0:
            print(f"[bench_ingest] FAIL: no shm-lane evidence in the "
                  f"monitor dir ({mon_dir}): {ev} — same-host readers "
                  "should have granted the lane and shipped batches "
                  "out-of-band", file=sys.stderr)
            ok = False
    print(f"[bench_ingest] smoke {'PASS' if ok else 'FAIL'}",
          flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
