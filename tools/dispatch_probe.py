"""Tunnel/runtime characterization for the axon TPU backend.

Separates three costs that a 48 ms ResNet step could hide (VERDICT r1
#2: '16% MFU and unexamined is not acceptable'):

* per-dispatch overhead — a chain of tiny dependent ops; if each
  execute pays an RPC round-trip instead of pipelining, per-step time
  floors at the round-trip
* compute-rate sanity — a big bf16 matmul chain (expected ~near peak:
  197 TFLOP/s on v5e)
* H2D bandwidth + fence latency — device_put of a large array, and the
  readback fence cost the framework uses for timing

Prints one JSON line per measurement.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _bootstrap  # noqa: F401  (makes JAX_PLATFORMS effective)
import jax
import jax.numpy as jnp
import numpy as np


def fence(x) -> None:
    np.asarray(jax.tree.leaves(x)[0].ravel()[:1])


def timed_chain(step, x, n, warmup=3):
    for _ in range(warmup):
        x = step(x)
    fence(x)
    t0 = time.perf_counter()
    for _ in range(n):
        x = step(x)
    fence(x)
    return (time.perf_counter() - t0) / n


def main():
    dev = jax.devices()[0]
    print(json.dumps({"backend": jax.default_backend(),
                      "device": str(dev)}))

    # 1. tiny dependent ops: pure dispatch/pipeline overhead
    tiny = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros((8, 8))
    dt = timed_chain(tiny, x, 200)
    print(json.dumps({"metric": "tiny_op_per_dispatch_ms",
                      "value": round(dt * 1e3, 3)}))

    # 2. big matmul chain: compute-rate sanity (bf16 MXU)
    n = 4096
    a = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def mm(x):
        for _ in range(8):
            x = jnp.dot(x, x) / jnp.bfloat16(n)
        return x

    dt = timed_chain(mm, a, 10)
    tflops = 8 * 2 * n**3 / dt / 1e12
    print(json.dumps({"metric": "bf16_matmul_tflops", "value": round(tflops, 1),
                      "chain_ms": round(dt * 1e3, 2)}))

    # 3. H2D bandwidth (100 MB uint8) + fence latency
    host = np.zeros(100 * 1024 * 1024, np.uint8)
    t0 = time.perf_counter()
    for _ in range(3):
        d = jax.device_put(host, dev)
        fence(d)
    dt = (time.perf_counter() - t0) / 3
    print(json.dumps({"metric": "h2d_gbps", "value": round(len(host) / dt / 1e9, 2),
                      "put_ms": round(dt * 1e3, 1)}))

    s = jnp.zeros(())
    t0 = time.perf_counter()
    for _ in range(20):
        fence(s + 1.0)
    dt = (time.perf_counter() - t0) / 20
    print(json.dumps({"metric": "scalar_fence_roundtrip_ms",
                      "value": round(dt * 1e3, 2)}))


if __name__ == "__main__":
    main()
