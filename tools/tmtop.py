#!/usr/bin/env python
"""tmtop — live fleet view from the telemetry collector's JSONL.

Tails ``fleet.jsonl`` (the collector's merged stream; every exporter
ships a metrics snapshot event every couple of seconds) and renders
one row per fleet process: the fleet role (router / prefill / serve /
ingest / ... — derived from the exporter-name prefix, so a
disaggregated serving fleet reads at a glance), step rate and p50,
exchange / RPC p99s,
decode queue depth and overload count, exporter drop counter, and
restart counters — the "is the fleet healthy and busy" question at a
glance, without ssh-ing into K processes to read K files.

Step RATES are derived from consecutive snapshots of each process's
``step_ms`` count (the snapshot itself only carries totals), so the
first frame shows dashes until a second snapshot lands.

Usage:
    python tools/tmtop.py RUNDIR_OR_FLEET_JSONL [--interval 2]
    python tools/tmtop.py RUNDIR --once        # one frame (tests/CI)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _fleet_path(target: str) -> str:
    if os.path.isdir(target):
        return os.path.join(target, "fleet.jsonl")
    return target


def read_records(path: str, offset: int = 0) -> tuple[list[dict], int]:
    """Records after byte ``offset``; returns (records, new offset).
    Restarts from 0 when the file shrank (rotation)."""
    out: list[dict] = []
    try:
        size = os.path.getsize(path)
        if size < offset:
            offset = 0  # rotated under us
        with open(path, encoding="utf-8") as f:
            f.seek(offset)
            for line in f:
                if not line.endswith("\n"):
                    break  # torn tail; re-read next frame
                offset += len(line.encode("utf-8"))
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out, offset


class Fleet:
    """Latest metrics snapshot per process + step-rate deltas."""

    def __init__(self):
        self.latest: dict[tuple, dict] = {}
        self.prev_steps: dict[tuple, tuple[float, float]] = {}
        self.rates: dict[tuple, float] = {}

    def feed(self, records: list[dict]) -> None:
        for r in records:
            if r.get("event") != "metrics":
                continue
            key = (r.get("role"), r.get("pid"))
            self.latest[key] = r
            count = sum(
                s.get("count") or 0 for s in r.get("snapshot", [])
                if s.get("name") == "step_ms")
            ts = float(r.get("t_wall") or 0.0)
            prev = self.prev_steps.get(key)
            if prev is not None and ts > prev[0]:
                self.rates[key] = (count - prev[1]) / (ts - prev[0])
            self.prev_steps[key] = (ts, count)

    def rows(self) -> list[dict]:
        out = []
        for (role, pid), rec in sorted(self.latest.items(),
                                       key=lambda kv: str(kv[0])):
            snap = rec.get("snapshot", [])

            def series(name, field, agg=max, default=None):
                vals = [s.get(field) for s in snap
                        if s.get("name") == name
                        and s.get(field) is not None]
                return agg(vals) if vals else default

            out.append({
                "role": role, "fleet": fleet_of(role), "pid": pid,
                "rank": rec.get("rank"),
                "age_s": time.time() - float(rec.get("t_wall") or 0),
                "rate": self.rates.get((role, pid)),
                "step_p50": series("step_ms", "p50"),
                "exch_p99": series("exchange_ms", "p99")
                or series("span_ms", "p99"),
                "rpc_p99": series("service/rpc_ms", "p99")
                or series("service/client_rpc_ms", "p99")
                or series("rpc/handshake_ms", "p99"),
                "queue": series("decode/pending", "value", agg=sum)
                or series("serving/queue_depth", "value", agg=sum),
                "overload": series("decode/overloaded_total", "value",
                                   agg=sum),
                "drops": series("monitor/export_dropped_total",
                                "value", agg=sum, default=0),
                "restarts": (series("service/shard_restarts_total",
                                    "value", agg=sum, default=0) or 0)
                + (series("monitor/collector_restarts_total",
                          "value", agg=sum, default=0) or 0),
            })
        return out


# fleet roles, by exporter-name prefix (the monitor session names:
# router{pid}, prefill{pid}, serve{pid}, ingest_reader{i}_{pid}, ...).
# "service" before "serve": service{pid} is the param service, not a
# serving replica.  Anything unrecognized (rank0 trainers) is "train".
_FLEET_PREFIXES = ("router", "prefill", "service", "serve", "ingest",
                   "shard", "collector", "aggregate")


def fleet_of(role) -> str:
    r = str(role or "")
    for p in _FLEET_PREFIXES:
        if r.startswith(p):
            return p
    return "train"


def _fmt(v, spec="{:.1f}") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return spec.format(v)
    return str(v)


def render(rows: list[dict], path: str, file=None) -> None:
    file = file if file is not None else sys.stdout
    cols = [("role", 18), ("fleet", 9), ("pid", 7), ("rank", 4),
            ("age", 6),
            ("step/s", 7), ("p50ms", 8), ("exch p99", 9),
            ("rpc p99", 8), ("queue", 6), ("ovld", 5), ("drops", 6),
            ("rst", 4)]
    print(f"tmtop — {path} — {time.strftime('%H:%M:%S')} — "
          f"{len(rows)} processes", file=file)
    print(" ".join(f"{name:>{w}}" for name, w in cols), file=file)
    for r in rows:
        vals = [str(r["role"])[:18], r["fleet"],
                _fmt(r["pid"], "{}"),
                _fmt(r["rank"], "{}"), _fmt(r["age_s"], "{:.0f}"),
                _fmt(r["rate"], "{:.2f}"), _fmt(r["step_p50"]),
                _fmt(r["exch_p99"]), _fmt(r["rpc_p99"]),
                _fmt(r["queue"], "{:.0f}"),
                _fmt(r["overload"], "{:.0f}"),
                _fmt(r["drops"], "{:.0f}"),
                _fmt(r["restarts"], "{:.0f}")]
        print(" ".join(f"{v:>{w}}" for v, (_, w) in zip(vals, cols)),
              file=file)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="live fleet view over the telemetry collector's "
                    "merged JSONL (docs/OBSERVABILITY.md)")
    ap.add_argument("target", help="fleet.jsonl or the run dir")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (CI/tests)")
    args = ap.parse_args(argv)

    path = _fleet_path(args.target)
    fleet = Fleet()
    offset = 0
    while True:
        records, offset = read_records(path, offset)
        fleet.feed(records)
        if not args.once:
            print("\x1b[2J\x1b[H", end="")
        render(fleet.rows(), path)
        if args.once:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `tmtop.py ... | head` is a normal use
        sys.exit(0)
