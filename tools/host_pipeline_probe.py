"""Host-side ingest rate, measured WITHOUT a chip (VERDICT r2 weak #3).

The e2e bench leg on the axon tunnel is H2D-bound (~0.03 GB/s), which
says nothing about whether the HOST pipeline could feed a real TPU VM
(tens of GB/s H2D).  This probe times exactly what the host does per
batch in each mode, on the real data path (`ImageNet_data`):

* ``device`` mode (the default economics): gather + stack raw uint8
  store images — the host's only job when augmentation runs on-device
  (`ops/augment.py`).
* ``host`` mode (reference loader semantics): the same plus host-side
  crop/flip/normalize to float32.

Run with synthetic pools (no data needed) or ``--data-dir`` npz shards
(the real decode/stream path).  One JSON line per mode:

    python tools/host_pipeline_probe.py --batch 128 --batches 40
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import _bootstrap  # noqa: F401,E402  (makes JAX_PLATFORMS effective)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128,
                    help="global batch (one chip's worth = 128)")
    ap.add_argument("--batches", type=int, default=40)
    ap.add_argument("--store", type=int, default=256)
    ap.add_argument("--crop", type=int, default=224)
    ap.add_argument("--data-dir", default=None,
                    help="shard dir — .x.npy pairs and/or .npz "
                         "(default: synthetic pool)")
    args = ap.parse_args()

    from theanompi_tpu.data.imagenet import ImageNet_data

    out = []
    for mode, on_device in (("device", True), ("host", False)):
        ds = ImageNet_data(
            data_dir=args.data_dir, crop=args.crop,
            synthetic_n=args.batch * (args.batches + 2),
            synthetic_pool=256, synthetic_store=args.store,
            augment_on_device=on_device)
        def stream():
            epoch = 0
            while True:  # cross epochs: reshuffle + file reopen included
                yield from ds.train_batches(epoch, args.batch)
                epoch += 1

        it = stream()
        x, y = next(it)  # warm the pool/file cache outside the timer
        t0 = time.perf_counter()
        n = 0
        for _ in range(args.batches):
            x, y = next(it)
            n += len(y)
        dt = time.perf_counter() - t0
        rec = {
            "mode": mode,
            "synthetic": ds.synthetic,
            "batch": args.batch,
            "img_per_sec": round(n / dt, 1),
            "ms_per_batch": round(dt / args.batches * 1e3, 2),
            "batch_mb": round(
                sum(a.nbytes for a in (x, y)) / 1e6, 1),
            "dtype": str(x.dtype),
        }
        out.append(rec)
        print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
