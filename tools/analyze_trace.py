"""Summarize a ``jax.profiler`` trace into an op-level time breakdown.

The MFU work (VERDICT r1 #2) needs to attribute step time to ops
before attacking it; TensorBoard's profile plugin isn't in this image,
so this parses the Chrome-trace JSON that ``jax.profiler.trace`` /
``utils/profiling.py`` (``THEANOMPI_TPU_PROFILE=dir``) writes and
prints, per trace: total span, busiest thread, and the top ops by
summed duration with a coarse category (conv / matmul / fusion /
copy / collective / infeed).

Usage:
    python tools/analyze_trace.py /tmp/trace_dir [--top 30]
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys


def find_traces(root: str) -> list[str]:
    pats = [os.path.join(root, "**", "*.trace.json.gz"),
            os.path.join(root, "**", "*.trace.json")]
    out: list[str] = []
    for p in pats:
        out.extend(glob.glob(p, recursive=True))
    return sorted(out)


def load_events(path: str) -> list[dict]:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as f:
        doc = json.load(f)
    return [e for e in doc.get("traceEvents", [])
            if e.get("ph") == "X" and "dur" in e]


CATEGORIES = (
    # "cast" must precede "conv": substring "conv" matches "convert"
    ("cast", ("convert",)),
    ("conv", ("conv",)),
    ("matmul", ("dot", "einsum", "matmul")),
    ("collective", ("all-reduce", "all-gather", "all-to-all",
                    "collective", "reduce-scatter", "permute", "psum")),
    ("copy/transpose", ("copy", "transpose", "bitcast", "reshape")),
    ("infeed/outfeed", ("infeed", "outfeed", "transfer")),
    ("fusion", ("fusion", "fused")),
)


def categorize(name: str) -> str:
    low = name.lower()
    for cat, keys in CATEGORIES:
        if any(k in low for k in keys):
            return cat
    return "other"


def summarize(path: str, top: int) -> None:
    events = load_events(path)
    if not events:
        print(f"{path}: no complete events")
        return
    # pick the device op stream as the (pid, tid) with the largest
    # interval-UNION busy time: host threads carry nested runtime/
    # Python spans whose summed durations would out-count the real op
    # stream if we ranked by plain sums
    def interval_union(evs) -> float:
        union, cur0, cur1 = 0.0, None, None
        for ev in sorted(evs, key=lambda e: e["ts"]):
            s, e_ = ev["ts"], ev["ts"] + ev["dur"]
            if cur1 is None or s > cur1:
                union += 0.0 if cur1 is None else cur1 - cur0
                cur0, cur1 = s, e_
            else:
                cur1 = max(cur1, e_)
        return union if cur1 is None else union + (cur1 - cur0)

    streams: dict[tuple, list] = collections.defaultdict(list)
    for e in events:
        streams[(e.get("pid"), e.get("tid"))].append(e)
    (pid, tid), union_us = max(
        ((k_, interval_union(v)) for k_, v in streams.items()),
        key=lambda kv: kv[1])
    stream = streams[(pid, tid)]
    stream_us = sum(e["dur"] for e in stream)
    t0 = min(e["ts"] for e in stream)
    t1 = max(e["ts"] + e["dur"] for e in stream)
    span_us = t1 - t0

    by_op: dict[str, list[float]] = collections.defaultdict(
        lambda: [0.0, 0])
    by_cat: dict[str, float] = collections.defaultdict(float)
    for e in stream:
        rec = by_op[e["name"]]
        rec[0] += e["dur"]
        rec[1] += 1
        by_cat[categorize(e["name"])] += e["dur"]

    print(f"== {os.path.relpath(path)}")
    print(f"   busiest stream pid={pid} tid={tid}: "
          f"{union_us / 1e3:.2f} ms busy over {span_us / 1e3:.2f} ms span "
          f"({100 * union_us / max(span_us, 1):.1f}% occupancy, "
          f"{len(stream)} events; op shares below sum nested spans)")
    print("   -- by category --")
    for cat, us in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        print(f"   {100 * us / stream_us:5.1f}%  {us / 1e3:9.2f} ms  {cat}")
    print(f"   -- top {top} ops --")
    rows = sorted(by_op.items(), key=lambda kv: -kv[1][0])[:top]
    for name, (us, n) in rows:
        print(f"   {100 * us / stream_us:5.1f}%  {us / 1e3:9.2f} ms  "
              f"x{n:<4d} {name[:90]}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=30)
    args = ap.parse_args()
    traces = find_traces(args.trace_dir)
    if not traces:
        print(f"no *.trace.json[.gz] under {args.trace_dir}", file=sys.stderr)
        return 1
    for t in traces:
        summarize(t, args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
