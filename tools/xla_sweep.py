"""Config-driven XLA flag sweep + A/B attribution reports (ISSUE 3).

Round 4's verdict: the MFU account landed and then "no optimization,
no XLA-flag sweep, no fusion experiment was attempted".  This harness
closes the loop, in three subcommands:

``emit``
    Write queue-ready experiments (``[[name, argv, timeout_s], ...]``,
    the ``run_tpu_queue.py --exps-json`` format) for FLAGS x MODEL
    throughput points plus the ResNet-50 before/after *profile* pair —
    every lever lands with an xplane capture so the win/loss is
    attributed per category, not just a single img/s number.  The flag
    sets come from ``SWEEPS`` (or ``--config`` JSON: {name: flags}).

``report BEFORE.json AFTER.json``
    Diff two ``analyze_xplane.py --out`` accounts: per-category
    ms/step deltas, totals, and (when both captured with ``--copies``)
    per-producer copy-done deltas.  This is the before/after evidence
    format every optimization in this repo must ship with.

``expected``
    Write the committed expected-delta table for the queued ResNet-50
    pair (artifacts/xla_sweep_expected.md) — the prediction is on
    record BEFORE the tunnel window, so the after-capture grades the
    model of the step, not just the step.

Pure helpers (``ab_report``, ``build_entries``) are unit-tested in
tests/test_xplane_tool.py without tensorflow or a chip.

Usage:
    python tools/xla_sweep.py emit --out artifacts/queue_xla_sweep_exps.json
    python tools/xla_sweep.py report before.json after.json [--out ab.json]
    python tools/xla_sweep.py expected --out artifacts/xla_sweep_expected.md
"""

from __future__ import annotations

import argparse
import json
import os
import sys

TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS)

#: flag sets to sweep — each lever targets a named residual of the
#: account (artifacts/mfu_account.json).  Keep this list short: every
#: entry costs a compile (~40 s) + timed run in a scarce tunnel window.
SWEEPS = {
    # the r3/r5 baseline — revalidates the 2 622 img/s point in the
    # same window so deltas aren't window-to-window noise
    "base": "",
    # latency-hiding scheduler: targets the 1 146 tiny MSA param
    # prefetches (1.42 ms of latency, not bandwidth) + 0.62 ms
    # async-done by overlapping them under the conv stream
    "lhs": "--xla_tpu_enable_latency_hiding_scheduler=true",
    # bigger scoped VMEM: fewer activation spills (0.93 ms) and fewer
    # prefetch/writeback bounces for the wide stage-exit shapes
    "vmem64m": "--xla_tpu_scoped_vmem_limit_kib=65536",
    # both levers together — the expected winner
    "lhs_vmem64m": ("--xla_tpu_enable_latency_hiding_scheduler=true "
                    "--xla_tpu_scoped_vmem_limit_kib=65536"),
}

#: (model-point name, extra queue_resnet_point args) — the MODEL axis
MODELS = {
    "resnet_k4_b128": ["--k", "4", "--batch", "128"],
    "resnet_k4_b128_s2d": ["--k", "4", "--batch", "128",
                           "--stem", "s2d"],
}


def build_entries(sweeps: dict[str, str] | None = None,
                  models: dict[str, list[str]] | None = None,
                  trace_root: str = "artifacts/tpu_trace_sweep") -> list:
    """[[name, argv, timeout_s], ...] — run_tpu_queue --exps-json rows.

    Throughput: flags x models via queue_resnet_point.  Profiles: the
    ResNet-50 A/B pair — 'before' re-captures the current default step
    (same code as the committed r3 account, donation fix included) and
    'after' flips the fused Pallas epilogues + maxpool, both through
    perf_probe's uint8 flagship staging with an xplane trace, so
    ``analyze_xplane --copies`` accounts can be diffed row-by-row with
    the ``report`` subcommand.
    """
    sweeps = SWEEPS if sweeps is None else sweeps
    models = MODELS if models is None else models
    py = sys.executable or "python"
    qp = os.path.join("tools", "queue_resnet_point.py")
    pp = os.path.join("tools", "perf_probe.py")
    entries = []
    for mname, margs in models.items():
        for sname, flags in sweeps.items():
            argv = [py, qp, *margs]
            if flags:
                argv += ["--xla-flags", flags]
            entries.append([f"sweep_{mname}_{sname}", argv, 900])
    # the before/after PROFILE pair (ResNet-50 b=128, flagship uint8
    # staging, 20 timed steps + 5 traced): before = default impls,
    # after = fused scale-bias-relu + argmax maxpool backward
    for tag, impl_args in (
            ("before", []),
            ("after_fused", ["--bn-act-impl", "pallas",
                             "--pool-impl", "pallas"])):
        entries.append([
            f"resnet_ab_{tag}_profile",
            [py, pp, "--batch", "128", "--steps", "20",
             "--variant", "uint8",
             "--trace", f"{trace_root}/{tag}", *impl_args],
            1800])
    return entries


def _get_report(account: dict) -> dict:
    """Accept a full ``analyze_xplane --out`` dict or a bare report."""
    return account.get("report", account)


def ab_report(before: dict, after: dict) -> dict:
    """Per-category (and per-copy-producer) delta of two accounts."""
    rb, ra = _get_report(before), _get_report(after)
    cats = {}
    for k in {**rb["categories"], **ra["categories"]}:
        b = rb["categories"].get(k, {})
        a = ra["categories"].get(k, {})
        bm = b.get("ms_per_step", 0.0)
        am = a.get("ms_per_step", 0.0)
        cats[k] = {
            "before_ms": bm, "after_ms": am,
            "delta_ms": round(am - bm, 3),
            "before_events": b.get("events_per_step", 0),
            "after_events": a.get("events_per_step", 0),
        }
    tb = rb["totals"]["device_busy_ms_per_step"]
    ta = ra["totals"]["device_busy_ms_per_step"]
    out = {
        "totals": {
            "before_ms": tb, "after_ms": ta,
            "delta_ms": round(ta - tb, 3),
            "delta_pct": round(100 * (ta - tb) / tb, 1) if tb else 0.0,
        },
        "categories": dict(sorted(cats.items(),
                                  key=lambda kv: kv[1]["delta_ms"])),
    }
    cb = before.get("copy_attribution")
    ca = after.get("copy_attribution")
    if cb and ca:
        rows_b = {r["producer"]: r for r in cb["rows"]}
        rows_a = {r["producer"]: r for r in ca["rows"]}
        copies = {}
        for k in {**rows_b, **rows_a}:
            bm = rows_b.get(k, {}).get("ms_per_step", 0.0)
            am = rows_a.get(k, {}).get("ms_per_step", 0.0)
            copies[k] = {"before_ms": bm, "after_ms": am,
                         "delta_ms": round(am - bm, 3)}
        out["copy_producers"] = dict(
            sorted(copies.items(), key=lambda kv: kv[1]["delta_ms"]))
        out["copy_totals"] = {
            "before_ms": cb["copy_done_ms_per_step"],
            "after_ms": ca["copy_done_ms_per_step"],
            "delta_ms": round(ca["copy_done_ms_per_step"]
                              - cb["copy_done_ms_per_step"], 3),
        }
    return out


def print_report(rep: dict) -> None:
    t = rep["totals"]
    print(f"# device-busy {t['before_ms']} -> {t['after_ms']} ms/step "
          f"({t['delta_pct']:+.1f}%)")
    print(f"{'category':<26}{'before':>9}{'after':>9}{'delta':>9}"
          f"{'ev b/a':>12}")
    for k, c in rep["categories"].items():
        print(f"{k[:25]:<26}{c['before_ms']:9.3f}{c['after_ms']:9.3f}"
              f"{c['delta_ms']:+9.3f}"
              f"{c['before_events']:>6}/{c['after_events']:<5}")
    if "copy_producers" in rep:
        ct = rep["copy_totals"]
        print(f"\n# copy-done {ct['before_ms']} -> {ct['after_ms']} "
              f"ms/step ({ct['delta_ms']:+.3f})")
        for k, c in list(rep["copy_producers"].items())[:15]:
            print(f"{c['before_ms']:9.3f}{c['after_ms']:9.3f}"
                  f"{c['delta_ms']:+9.3f}  {k}")


EXPECTED_MD = """\
# Expected deltas for the queued ResNet-50 A/B pair

Committed BEFORE the tunnel window (ISSUE 3 acceptance): the
`resnet_ab_before_profile` / `resnet_ab_after_fused_profile` entries
in `artifacts/queue_xla_sweep_exps.json` capture both accounts; grade
this table with

    python tools/analyze_xplane.py artifacts/tpu_trace_sweep/before  --copies --out /tmp/b.json
    python tools/analyze_xplane.py artifacts/tpu_trace_sweep/after_fused --copies --out /tmp/a.json
    python tools/xla_sweep.py report /tmp/b.json /tmp/a.json

Baseline: the r3 capture's 46.90 ms device-busy step
(`artifacts/mfu_account.json`, `artifacts/copy_attribution_r03.json`).

| lever | slice attacked (r3 measured) | expected after | basis |
|---|---|---|---|
| fused scale-bias-relu epilogue (`bn_act_impl='pallas'`, ops/fused_bn.py) | loop fusion 5.81 ms / 269 ev (adds+relu 678-992 GB/s) | 4.3-5.0 ms | the 3 stage-1 `BottleneckBlock_*/add` exit epilogues alone are 2.7 ms at 83% HBM; fusing BN-apply+add+relu into one stream removes one full read+write of each exit activation (~1/3 of those bytes). Fwd-only win — bwd mask recompute streams the same bytes XLA's does |
| maxpool argmax backward (`pool_impl='pallas'`, ops/maxpool_pallas.py) | select-and-scatter 0.761 ms at 74% HBM peak | 0.35-0.45 ms | backward streams g+idx+dx ~282 MB instead of ~460 MB (kernel docstring); bound 0.34 ms at the slice's own 608 GB/s |
| `--xla_tpu_enable_latency_hiding_scheduler=true` | 1 146 param-vec MSA copies 1.42 ms (latency-bound, ~1-7 us each) + async-done 0.62 ms | 0.7-1.2 ms combined | scheduler overlaps the tiny prefetches under the conv stream; per-copy latency doesn't shrink, exposure does |
| `--xla_tpu_scoped_vmem_limit_kib=65536` | activation spill prefetch/writeback ~0.9 ms | 0.5-0.8 ms | r5 sweep precedent; bigger scoped VMEM keeps stage-exit activations resident. May TRADE against conv rate (less pipelining headroom) — that is why every flag point re-measures throughput, not just the account |

**Not graded by this pair — staged-batch donation
(`donate_batch`, parallel/bsp.py).** It only changes the stacked
(k>1 / grad-accum) programs, and every batch-replaying queue harness
(perf_probe, queue_resnet_point, bench.py's device leg) necessarily
opts out with `donate_batch=False` — a replayed batch cannot be
donated.  The profile pair above is a single-step program, so its
copy-done delta excludes donation entirely; grade that lever from a
prefetcher-fed k>1 `run_bsp_session` run — e.g.
`THEANOMPI_TPU_PROFILE=dir python -m theanompi_tpu.launcher BSP -m
cifar10 --epochs 1 --set steps_per_call=4` — in a later window.
(NOT bench.py: both its legs reuse ONE compiled program whose batch
donation is off because leg 1 replays staged batches.)  Until then
the donation is asserted structurally by the lowering tests
(tests/test_multi_step.py::TestStagedBatchDonation).

Net expectation for the profile pair (fused epilogues + maxpool only,
donation excluded): device-busy 46.9 -> 44.6-45.9 ms/step
(~2 570 -> ~2 630-2 700 img/s/chip at b=128), convs unchanged at
~93% of their HBM-implied ceiling.  Anything outside these ranges
means the model of the step is wrong somewhere — find where before
believing the number.
"""


def main() -> int:
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)
    e = sub.add_parser("emit")
    e.add_argument("--out",
                   default=os.path.join(REPO, "artifacts",
                                        "queue_xla_sweep_exps.json"))
    e.add_argument("--config", default=None,
                   help="JSON {name: xla-flags} overriding the "
                        "built-in SWEEPS")
    r = sub.add_parser("report")
    r.add_argument("before")
    r.add_argument("after")
    r.add_argument("--out", default=None)
    x = sub.add_parser("expected")
    x.add_argument("--out",
                   default=os.path.join(REPO, "artifacts",
                                        "xla_sweep_expected.md"))
    args = ap.parse_args()

    if args.cmd == "emit":
        sweeps = None
        if args.config:
            with open(args.config) as fh:
                sweeps = json.load(fh)
        entries = build_entries(sweeps)
        with open(args.out, "w") as fh:
            json.dump(entries, fh, indent=1)
        print(f"wrote {len(entries)} queue entries to {args.out}")
        print(f"run with: python tools/run_tpu_queue.py --gate "
              f"--exps-json {args.out}")
        return 0
    if args.cmd == "report":
        with open(args.before) as fh:
            before = json.load(fh)
        with open(args.after) as fh:
            after = json.load(fh)
        rep = ab_report(before, after)
        print_report(rep)
        if args.out:
            with open(args.out, "w") as fh:
                json.dump(rep, fh, indent=1)
            print(f"\nwrote {args.out}")
        return 0
    with open(args.out, "w") as fh:
        fh.write(EXPECTED_MD)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
