"""Deep-dive on the near-zero-FLOP device time in an xplane capture.

The round-4 MFU account (artifacts/mfu_account.json) showed ~21% of
ResNet-50 device-busy time in categories producing ~1% of the FLOPs:
loop fusion 5.8 ms, copy-done 2.4 ms (1334 events!), select-and-scatter
0.8 ms, async-done 0.6 ms.  The round-4 verdict's #1 task is to spend
that account: name what those events ARE and either recover the time or
prove each slice sits at its own bandwidth bound.  This tool produces
the evidence (artifacts/fusion_deepdive.json):

- loop fusions aggregated by JAX source op (``tf_op`` stat) + output
  shape, with per-row bytes and measured GB/s — shows the residual
  adds / relu / BN-backward reductions individually;
- copy-done events split into size classes (the <=8 KiB parameter
  prefetches stall ~1 us each regardless of size — latency, not
  bandwidth; the >=1 MiB activation spills stream at HBM rate);
- select-and-scatter / async ops named;
- a per-slice verdict: measured GB/s vs the 819 GB/s v5e HBM peak.

Pure-aggregation helpers are unit-tested in tests/test_xplane_tool.py's
style; the proto walk reuses tools/analyze_xplane.py.

Usage:
    python tools/fusion_deepdive.py artifacts/tpu_trace \
        [--out artifacts/fusion_deepdive.json]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# the shape/size/source-op helpers moved to analyze_xplane (its
# --copies attribution needs them too); re-exported here so existing
# imports keep working
from analyze_xplane import (SUB_RESOLUTION_MS, _load_xspace,  # noqa: E402,F401
                            copy_size_class, extract_device_events,
                            find_xplane, hlo_output_part, shrink_tf_op)


def out_shape(name: str) -> str:
    m = re.search(r"\w+\[[\d,]+\]", hlo_output_part(name))
    return m.group(0) if m else "?"


def deepdive(events: list[dict], n_steps: int,
             peak_hbm_gbps: float) -> dict:
    loops = defaultdict(lambda: [0, 0, 0])   # dur, bytes, n
    copies = defaultdict(lambda: [0, 0, 0])
    named = defaultdict(lambda: [0, 0, 0])
    for e in events:
        cat = e["category"]
        if cat == "loop fusion":
            k = (shrink_tf_op(e.get("tf_op", "")), out_shape(e["name"]))
            a = loops[k]
        elif cat == "copy-done":
            a = copies[copy_size_class(e["name"])]
        elif cat in ("select-and-scatter", "async-done", "async-start",
                     "output fusion", "non-fusion elementwise"):
            k = (cat, shrink_tf_op(e.get("tf_op", "")) or
                 e["display"].rstrip("0123456789."))
            a = named[k]
        else:
            continue
        a[0] += e["dur_ps"]
        a[1] += e["bytes"]
        a[2] += 1

    def rows(table, top=None):
        out = []
        items = sorted(table.items(), key=lambda kv: -kv[1][0])
        for k, (dur, nbytes, n) in (items[:top] if top else items):
            ms = dur / 1e9 / n_steps
            # same guards as analyze_xplane: sub-resolution rows can't
            # support a rate; fractions far past peak are bookkeeping
            # (VMEM re-reads / async waits), not HBM streaming
            unreliable = ms < SUB_RESOLUTION_MS
            gbs = nbytes / (dur / 1e12) / 1e9 \
                if dur and not unreliable else 0.0
            frac = round(gbs / peak_hbm_gbps, 3) if peak_hbm_gbps \
                and not unreliable else None
            row = {
                "key": "/".join(k) if isinstance(k, tuple) else k,
                "ms_per_step": round(ms, 3),
                "events_per_step": n // n_steps,
                "gbytes_per_s": round(gbs, 1),
                "hbm_fraction": frac,
                "us_per_event": round(dur / 1e6 / n, 1) if n else 0.0,
            }
            if unreliable:
                row["rates_unreliable"] = True
            elif frac is not None and frac > 1.25:
                row["accounting_artifact"] = True
            out.append(row)
        return out

    return {
        "loop_fusions_by_source_op": rows(loops, top=30),
        "copy_done_by_size_class": rows(copies),
        "other_near_zero_flop": rows(named, top=20),
        "loop_fusion_total_ms": round(
            sum(v[0] for v in loops.values()) / 1e9 / n_steps, 3),
        "copy_done_total_ms": round(
            sum(v[0] for v in copies.values()) / 1e9 / n_steps, 3),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("path")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    pb = find_xplane(args.path)
    events, n_steps, info = extract_device_events(_load_xspace(pb))
    peak_bw = float(info.get("peak_hbm_bw_gigabytes_per_second", 0) or 0)
    report = deepdive(events, n_steps, peak_bw)

    print(f"# near-zero-FLOP deep dive — {n_steps} steps, "
          f"HBM peak {peak_bw:.0f} GB/s")
    print(f"\n== loop fusions by source op "
          f"(total {report['loop_fusion_total_ms']} ms/step) ==")
    print(f"{'ms/step':>8} {'n':>4} {'GB/s':>7} {'%HBM':>6}  source op / out shape")
    for r in report["loop_fusions_by_source_op"][:18]:
        print(f"{r['ms_per_step']:8.3f} {r['events_per_step']:4d} "
              f"{r['gbytes_per_s']:7.0f} "
              f"{100 * (r['hbm_fraction'] or 0):6.1f}  {r['key']}")
    print(f"\n== copy-done by size class "
          f"(total {report['copy_done_total_ms']} ms/step) ==")
    print(f"{'ms/step':>8} {'n':>5} {'GB/s':>7} {'us/copy':>8}  class")
    for r in report["copy_done_by_size_class"]:
        print(f"{r['ms_per_step']:8.3f} {r['events_per_step']:5d} "
              f"{r['gbytes_per_s']:7.0f} {r['us_per_event']:8.1f}  {r['key']}")
    print("\n== other near-zero-FLOP ==")
    for r in report["other_near_zero_flop"][:12]:
        print(f"{r['ms_per_step']:8.3f} {r['events_per_step']:5d} "
              f"{r['gbytes_per_s']:7.0f}  {r['key']}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"device": info, "n_steps": n_steps, **report},
                      f, indent=1)
        print(f"\nwrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
