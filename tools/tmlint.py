#!/usr/bin/env python
"""Repo entry point for the static checker suite (docs/ANALYSIS.md).

Loads ``theanompi_tpu.analysis`` WITHOUT executing the package
``__init__`` (which imports jax via compat): a stub parent module with
``__path__`` pointing at the real package directory is installed
first, so the subpackage resolves from the filesystem while the
parent's body never runs.  The gate is therefore pure stdlib end to
end — it runs on a cold box with a broken or absent jax install and
can never touch (or be wedged by) a device runtime, which is the
property preflight's first must-pass step depends on.  (The installed
``tmlint`` console script imports the real package instead — same
checkers, but it needs a working environment.)

    python tools/tmlint.py --gate
"""

from __future__ import annotations

import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

if "theanompi_tpu" not in sys.modules:
    _stub = types.ModuleType("theanompi_tpu")
    _stub.__path__ = [os.path.join(_REPO, "theanompi_tpu")]
    sys.modules["theanompi_tpu"] = _stub

from theanompi_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
