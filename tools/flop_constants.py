"""FLOP-accounting constants shared by the perf tools — import-free,
so log parsers (harvest_queue) never drag jax/the axon plugin in.

ResNet-50 training cost in 2xMAC FLOPs (the convention of the nominal
197 TF/s and tools/dispatch_probe.py's measured 2·n³ rates): forward =
4.09 GMAC = 8.2 GF @ 224x224, x ~3 for fwd+bwd.  The shape-by-shape
derivation lives in tools/conv_ladder.py and is pinned by
tests/test_conv_ladder.py.
"""

TRAIN_GFLOP_PER_IMAGE = 24.6
V5E_PEAK_TFLOPS = 197.0  # bf16, 2xMAC convention
