"""Shared tool bootstrap: make JAX_PLATFORMS effective.

This environment pre-registers the experimental axon TPU plugin via
sitecustomize, which ignores the JAX_PLATFORMS env var on its own — a
tool meant to run on CPU would silently touch (and possibly wedge) the
TPU tunnel.  Import this module AFTER putting the repo root on
sys.path and BEFORE first backend use.
"""

from __future__ import annotations

import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
