"""Per-op MFU ladder for the ResNet-50 BSP step (VERDICT r2 #2).

The committed performance model (docs/DESIGN.md) bounds the
*environment* (size-dependent matmul rates, dispatch floor, H2D);
this tool bounds the *model step*: it enumerates every distinct conv
shape in ResNet-50 (geometry mirrored from
``theanompi_tpu/models/resnet50.py`` — BottleneckBlock 1x1/3x3/1x1,
projection on the first block of each stage, conv7 or s2d stem), times
each shape's forward and forward+backward on the current backend, and
reconciles the weighted sum against the measured full-step time.  The
residual (full step − Σ convs) is the BN/elementwise/optimizer/psum
slice XLA fuses around the convs.

Run on the chip (via the TPU queue) for real numbers; runs on CPU for
tool validation at small batch.  Emits one JSON line per shape plus a
summary line:

    python tools/conv_ladder.py --batch 128 --out ladder.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _bootstrap  # noqa: F401,E402  (makes JAX_PLATFORMS effective)


def resnet50_convs(batch: int, stem: str = "conv7",
                   stage_sizes=(3, 4, 6, 3), width: int = 64):
    """(name, b, h_in, cin, cout, k, stride, count) for every distinct
    conv in one fwd pass, with multiplicity.  h_in is the INPUT spatial
    size; output spatial = h_in // stride (SAME padding throughout)."""
    convs = []
    if stem == "s2d":
        convs.append(("stem_s2d4x4", batch, 112, 12, width, 4, 1, 1))
    else:
        convs.append(("stem_conv7", batch, 224, 3, width, 7, 2, 1))

    cin = width                       # after the 3x3/2 maxpool: 56x56x64
    spatial = 56
    for s, n_blocks in enumerate(stage_sizes):
        feat, out = width * (2 ** s), 4 * width * (2 ** s)
        stride = 2 if s > 0 else 1
        # first block (projection + possible stride)
        convs += [
            (f"s{s}b0_proj1x1", batch, spatial, cin, out, 1, stride, 1),
            (f"s{s}b0_red1x1", batch, spatial, cin, feat, 1, 1, 1),
            (f"s{s}b0_mid3x3", batch, spatial, feat, feat, 3, stride, 1),
            (f"s{s}b0_exp1x1", batch, spatial // stride, feat, out, 1, 1, 1),
        ]
        spatial //= stride
        # remaining identical blocks
        if n_blocks > 1:
            m = n_blocks - 1
            convs += [
                (f"s{s}bN_red1x1", batch, spatial, out, feat, 1, 1, m),
                (f"s{s}bN_mid3x3", batch, spatial, feat, feat, 3, 1, m),
                (f"s{s}bN_exp1x1", batch, spatial, feat, out, 1, 1, m),
            ]
        cin = out
    return convs


def conv_gflops(b, h, cin, cout, k, stride) -> float:
    h_out = h // stride
    return 2.0 * b * h_out * h_out * k * k * cin * cout / 1e9


def time_shape(b, h, cin, cout, k, stride, dtype, n_iters, fence):
    import jax
    import jax.numpy as jnp
    from jax import lax

    pad = "SAME"
    x = jax.random.normal(jax.random.key(0), (b, h, h, cin), dtype)
    w = jax.random.normal(jax.random.key(1), (k, k, cin, cout), dtype)

    def conv(x, w):
        # output dtype == operand dtype, mirroring flax nn.Conv as the
        # models use it (models/resnet50.py dtype=compute_dtype, no
        # preferred_element_type); a f32 output here would also make
        # the VJP's transpose conv see a f32 cotangent against bf16
        # operands, which lax.conv_general_dilated rejects
        return lax.conv_general_dilated(
            x, w, (stride, stride), pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=dtype)

    fwd = jax.jit(conv)
    # fwd+bwd wrt both operands — primal + dgrad + wgrad, like
    # training.  value_and_grad, NOT grad: conv is linear, so under
    # plain grad the primal is dead code (the sum's cotangent is
    # constant ones and neither VJP reads the output) and only 2 of
    # the 3 GEMMs would be timed.  The sum accumulates in f32 so the
    # scalar stays finite at b=128 sizes.
    fb = jax.jit(jax.value_and_grad(
        lambda x, w: conv(x, w).astype(jnp.float32).sum(),
        argnums=(0, 1)))

    def bench(fn):
        out = fn(x, w)
        fence(out)                      # compile + settle
        t0 = time.perf_counter()
        for _ in range(n_iters):
            out = fn(x, w)
        fence(out)
        return (time.perf_counter() - t0) / n_iters * 1e3

    return bench(fwd), bench(fb)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--stem", default="conv7", choices=("conv7", "s2d"))
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--out", default=None, help="also append JSONL here")
    ap.add_argument("--step-ms", type=float, default=None,
                    help="measured full-step ms to reconcile against")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    def fence(tree):
        for leaf in jax.tree.leaves(tree):
            np.asarray(leaf.ravel()[:1])

    dtype = jnp.dtype(args.dtype)
    sink = open(args.out, "a", buffering=1) if args.out else None

    def emit(obj):
        line = json.dumps(obj)
        print(line, flush=True)
        if sink:
            sink.write(line + "\n")

    # out_dtype tags every row: v1 of this tool emitted f32 conv
    # outputs (+cast), v2 emits operand-dtype outputs — rows from the
    # two generations in one JSONL are not directly comparable, so
    # each row says which regime produced it (ADVICE r3 #3)
    emit({"event": "ladder_start", "backend": jax.default_backend(),
          "batch": args.batch, "stem": args.stem, "dtype": args.dtype,
          "out_dtype": args.dtype, "tool_version": 2})
    total_fwd = total_fb = total_gflops = 0.0
    for (name, b, h, cin, cout, k, stride, count) in resnet50_convs(
            args.batch, args.stem):
        g = conv_gflops(b, h, cin, cout, k, stride)
        fwd_ms, fb_ms = time_shape(b, h, cin, cout, k, stride, dtype,
                                   args.iters, fence)
        total_fwd += count * fwd_ms
        total_fb += count * fb_ms
        total_gflops += count * g
        emit({"conv": name, "h_in": h, "cin": cin, "cout": cout,
              "k": k, "stride": stride, "count": count,
              "out_dtype": args.dtype,
              "gflops_fwd": round(g, 2),
              "fwd_ms": round(fwd_ms, 3), "fwdbwd_ms": round(fb_ms, 3),
              "tflops_fwd": round(g / fwd_ms, 2),
              "tflops_fwdbwd": round(3 * g / fb_ms, 2),
              "total_ms": round(count * fb_ms, 2)})
    summary = {
        "event": "ladder_summary",
        "sum_fwd_ms": round(total_fwd, 2),
        "sum_fwdbwd_ms": round(total_fb, 2),
        "sum_gflops_fwd": round(total_gflops, 1),
        "tflops_fwdbwd": round(3 * total_gflops / total_fb, 2),
    }
    if args.step_ms:
        summary["measured_step_ms"] = args.step_ms
        summary["conv_fraction"] = round(total_fb / args.step_ms, 3)
        summary["residual_ms"] = round(args.step_ms - total_fb, 2)
    emit(summary)
    if sink:
        sink.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
