"""Microbench: XLA-composed vs Pallas LRN on the attached chip.

Run with no env overrides to hit the real TPU.  This measurement is
why 'auto' in ops/lrn.py resolves to the Pallas kernel on TPU (batch
64: fwd+bwd 4.35->2.94 ms at (55,55,96), 2.41->1.96 ms at
(27,27,256)); re-run it if either impl changes.

Usage: python tools/bench_lrn.py [batch]
"""

from __future__ import annotations

import os
import sys
import time

# NOTE: do NOT use PYTHONPATH for this — setting it breaks the axon
# TPU plugin's sitecustomize registration in this environment
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _bootstrap  # noqa: F401  (makes JAX_PLATFORMS effective)
import jax
import jax.numpy as jnp

from theanompi_tpu.ops import lrn


def bench(fn, x, n_iters=50):
    y = fn(x)
    y.block_until_ready()
    float(y.sum())  # readback fence (axon block_until_ready returns early)
    t0 = time.perf_counter()
    for _ in range(n_iters):
        y = fn(x)
    float(y.sum())
    return (time.perf_counter() - t0) / n_iters * 1e3


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    print(f"backend={jax.default_backend()}")
    # AlexNet's two LRN sites
    for shape in ((batch, 55, 55, 96), (batch, 27, 27, 256)):
        x = jax.random.normal(jax.random.key(0), shape, jnp.float32)
        for impl in ("xla", "pallas"):
            fwd = jax.jit(lambda v, i=impl: lrn(v, impl=i))
            grad = jax.jit(jax.grad(lambda v, i=impl: lrn(v, impl=i).sum()))
            t_f = bench(fwd, x)
            t_g = bench(grad, x)
            print(f"{shape} {impl:6s}: fwd {t_f:7.3f} ms  fwd+bwd {t_g:7.3f} ms")


if __name__ == "__main__":
    main()
