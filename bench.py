"""Benchmark: ResNet-50 ImageNet BSP training throughput (the driver's
primary metric — BASELINE.json: images/sec/chip, north-star ≥2500
img/s on a v5e-16 ⇒ 156.25 img/s/chip).

Two legs, one compile (VERDICT r1 next-round #3):

* **device-step**: the flagship BSP training step (on-device
  crop/flip/normalize + fwd + bwd + psum exchange + SGD update, bf16
  compute) over pre-staged uint8 batches — the images/sec/chip
  headline.
* **e2e**: the same step driven through the real pipeline
  (``train_iter``: synthetic-pool host batches → DevicePrefetcher →
  sharded device_put → step), wall-clock — proves the host can feed
  the chip.  The TPU-native data path ships raw uint8 and augments on
  device (ops/augment.py), so the one-core host only assembles
  batches.

Prints ONE JSON line ``{"metric": ..., "value": N, "unit":
"images/sec/chip", "vs_baseline": N, "detail": {...}}`` where detail
carries the e2e leg and the recorder cross-check (VERDICT r1 #6).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# NOTE: this (via theanompi_tpu/__init__ -> compat) imports jax at
# module scope — same as the module-level `import jax` further down,
# so the probe path's wedge isolation still relies on the SUBPROCESS
# probe (importing jax is safe; creating a backend is what hangs)
from theanompi_tpu import monitor
from theanompi_tpu.resilience.retry import RetryPolicy

# probe-retry backoff (resilience.retry): exponential 5s -> 30s with
# jitter replaces the old flat 30 s sleeps — early attempts re-probe a
# transient relay restart quickly, later ones stop hammering a wedge.
# max_attempts is unused here (the window is the probe loop's own
# deadline); only delay() is consumed.
_PROBE_BACKOFF = RetryPolicy(base_delay=5.0, max_delay=30.0,
                             multiplier=2.0, jitter=0.25,
                             name="bench_probe")

BASELINE_PER_CHIP = 2500.0 / 16.0  # north-star v5e-16 target, per chip
E2E_STEPS = int(os.environ.get("THEANOMPI_TPU_BENCH_E2E_STEPS", "64"))
BATCH_PER_CHIP = int(os.environ.get("THEANOMPI_TPU_BENCH_BATCH", "128"))
N_STEPS = int(os.environ.get("THEANOMPI_TPU_BENCH_STEPS", "30"))
# scanned multi-step cadence (ModelConfig.steps_per_call): k>1 runs k
# training iterations per device dispatch — bit-identical trajectory,
# amortizes the per-dispatch overhead that dominates on the tunnel.
# Default k adopted from the round-3 ON-CHIP ladder (k in {1,4,8} x
# batch {128,256} x stem, artifacts/tpu_queue_r03.jsonl): k=4 b=128
# conv7 won at 2622 img/s/chip vs 2561 at k=1 (+2.4%); at b=256 the
# ordering FLIPS — k=1 is the measured best (2498.36) and k=4 the
# worst (2488.85) — so the default is per-batch (4 at b<=128, 1
# above), not a flat 4 (ADVICE r3 #2); k=8 gains nothing anywhere.
# The k>1 default applies on the TPU backend ONLY: a round-3 CPU
# probe found the scanned ResNet body 13x slower per step on the CPU
# backend (a backend de-optimization, not a trajectory change), so
# CPU smoke runs keep k=1 unless THEANOMPI_TPU_BENCH_K is set
# explicitly — the backend check happens in main() after the probe
# determines the platform.
_BENCH_K_ENV = os.environ.get("THEANOMPI_TPU_BENCH_K")
STEPS_PER_CALL = (int(_BENCH_K_ENV) if _BENCH_K_ENV is not None
                  else (4 if BATCH_PER_CHIP <= 128 else 1))
if STEPS_PER_CALL < 1:
    raise SystemExit(f"THEANOMPI_TPU_BENCH_K must be >= 1, "
                     f"got {STEPS_PER_CALL}")
if STEPS_PER_CALL > E2E_STEPS:
    if _BENCH_K_ENV is not None:
        raise SystemExit(f"THEANOMPI_TPU_BENCH_K ({STEPS_PER_CALL}) must "
                         f"not exceed THEANOMPI_TPU_BENCH_E2E_STEPS "
                         f"({E2E_STEPS}) or the e2e leg would run zero "
                         "iterations")
    # defaulted k: clamp instead of aborting, so a lowered E2E_STEPS
    # smoke run (e.g. CI with E2E_STEPS=2) still works out of the box
    STEPS_PER_CALL = E2E_STEPS


# Probe window default 240 s (round-4: was 1800, which exceeded the
# DRIVER's own capture timeout — round 3's official record was an
# rc=124 empty tail because bench.py was still silently probing when
# the driver's `timeout` killed it.  Long tunnel-patience belongs in
# tools/run_tpu_queue.py; the driver-invoked path must resolve — with
# a parseable JSON line either way — inside the driver's patience.
# Builder-side runs that WANT the long window set
# THEANOMPI_TPU_BENCH_PROBE_S explicitly.)
PROBE_WINDOW_S = int(os.environ.get("THEANOMPI_TPU_BENCH_PROBE_S", "240"))
PROBE_ATTEMPT_S = int(os.environ.get("THEANOMPI_TPU_BENCH_PROBE_ATTEMPT_S",
                                     "150"))
# clamped to >=1: a zero/negative cadence would make the wait-slice
# loop in _run_probe_sub treat every attempt as instantly expired
HEARTBEAT_S = max(1.0, float(
    os.environ.get("THEANOMPI_TPU_BENCH_HEARTBEAT_S", "30")))

# The newest committed on-chip measurement, embedded in every failure
# record (VERDICT r4 #5: a wedged-tunnel round must still hand the
# driver a machine-readable number).  The `date` field makes staleness
# self-describing to consumers; UPDATE THIS (and BASELINE.md) when a
# new on-chip point lands — tools/harvest_queue.py prints the ladder.
LAST_VERIFIED_ON_CHIP = {
    "value": 2622.04,
    "unit": "images/sec/chip",
    "date": "2026-08-02",
    "source": "artifacts/tpu_queue_r03.jsonl (round-3 window, k=4 "
              "b=128 conv7; last DRIVER-verified: 2595.58, BENCH_r01)",
}

# Live status for the failure envelope: updated by the probe loop and
# the measurement legs, read by the SIGTERM/SIGINT handler so a killed
# run still emits one parseable JSON line (round-3 verdict #1).
# ``timeline`` is the machine-readable probe/phase event log: a
# device-init hang used to leave only a prose error string (r04 wedged
# 240 s with zero structured signal); now every attempt start, hang
# timeout, failure, and phase change lands here and rides the failure
# JSON, keeping BENCH_*.json comparable across rounds.
_STATUS = {"phase": "startup", "probe_attempts": 0, "last_error": "",
           "t0": time.monotonic(), "timeline": []}
_CURRENT_SUB = None  # Popen of the in-flight probe, for cleanup on kill


def _timeline(event: str, **fields) -> None:
    """Append one event to the machine-readable probe/phase timeline
    (bounded: a pathological retry loop must not bloat the record)."""
    if len(_STATUS["timeline"]) < 200:
        _STATUS["timeline"].append(
            {"t": round(time.monotonic() - _STATUS["t0"], 1),
             "event": event, **fields})


def _set_phase(phase: str) -> None:
    _STATUS["phase"] = phase
    _timeline("phase", phase=phase)
    monitor.progress(phase=phase)


def _failure_json(reason: str) -> str:
    return json.dumps({
        "metric": "resnet50_imagenet_bsp_images_per_sec_per_chip",
        "value": 0.0, "unit": "images/sec/chip", "vs_baseline": 0.0,
        "detail": {
            "error": reason,
            "phase": _STATUS["phase"],
            "probe_attempts": _STATUS["probe_attempts"],
            "last_error": _STATUS["last_error"],
            "elapsed_s": round(time.monotonic() - _STATUS["t0"], 1),
            # the partial probe timeline: attempt starts, per-attempt
            # wait durations, failures, last phase — machine-comparable
            # across rounds even when the run never measured anything
            "probe_timeline": _STATUS["timeline"],
            "note": "no measurement taken — last verified on-chip "
                    "numbers: BASELINE.md 'Measured' table",
            # machine-readable pointer so a failure record still
            # carries the last driver-checkable number (VERDICT r4 #5)
            "last_verified": LAST_VERIFIED_ON_CHIP,
        },
    })


def _install_kill_handler() -> None:
    """SIGTERM/SIGINT → flush a failure JSON line, then exit 1.

    The driver wraps bench.py in `timeout`, which SIGTERMs (then
    SIGKILLs) on expiry.  Round 3 died holding its output: stdout had
    nothing when the TERM landed, so the official record was an
    unparseable empty tail.  The handler makes every exit path emit
    exactly one JSON line; SIGKILL is the only unhandleable case, and
    the stderr heartbeat (below) leaves a diagnostic tail even then."""
    import signal

    def on_kill(signum, frame):
        sig = signal.Signals(signum).name
        try:
            if _CURRENT_SUB is not None:
                os.killpg(_CURRENT_SUB.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        if _STATUS["phase"] == "done":
            # success line already printed; a TERM landing during
            # interpreter/plugin teardown must not append a second
            # (failure) JSON line that a last-line parser would take
            os._exit(0)
        print(_failure_json(f"killed by {sig} during "
                            f"phase={_STATUS['phase']}"), flush=True)
        # plain exit, not sys.exit: the handler may interrupt arbitrary
        # frames (incl. finally blocks that would swallow SystemExit)
        os._exit(1)

    signal.signal(signal.SIGTERM, on_kill)
    signal.signal(signal.SIGINT, on_kill)


def _heartbeat(msg: str) -> None:
    """One line to STDERR — never stdout, which must stay a single
    JSON line — so a killed run leaves a human-readable tail."""
    el = time.monotonic() - _STATUS["t0"]
    print(f"[bench +{el:.0f}s] {msg}", file=sys.stderr, flush=True)


def _run_probe_sub(argv, timeout):
    """Run the probe with FILE-backed stdio and a process-group kill.

    ``subprocess.run(capture_output=True, timeout=...)`` deadlocks on
    this tunnel: the axon client spawns helper grandchildren that
    inherit the stdout pipe, so after the timeout kill the internal
    ``communicate()`` blocks forever on a pipe the orphans hold open
    (observed live in round 3: a 150 s probe still "running" at 9 min).
    Waits in <=HEARTBEAT_S slices, emitting a stderr status line per
    slice.  Returns (rc, stdout, stderr, timed_out)."""
    import signal
    import tempfile

    global _CURRENT_SUB
    with tempfile.TemporaryFile() as fo, tempfile.TemporaryFile() as fe:
        p = subprocess.Popen(argv, stdout=fo, stderr=fe,
                             start_new_session=True)
        _CURRENT_SUB = p
        deadline = time.monotonic() + timeout
        rc, timed_out = None, False
        while True:
            slice_s = min(HEARTBEAT_S, deadline - time.monotonic())
            if slice_s <= 0:
                timed_out = True
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                p.wait()
                break
            try:
                rc = p.wait(timeout=slice_s)
                break
            except subprocess.TimeoutExpired:
                _heartbeat(
                    f"probe attempt {_STATUS['probe_attempts']} still "
                    f"waiting on device init "
                    f"({deadline - time.monotonic():.0f}s left in "
                    "attempt)")
        _CURRENT_SUB = None
        fo.seek(0)
        fe.seek(0)
        return (rc, fo.read().decode(errors="replace"),
                fe.read().decode(errors="replace"), timed_out)


def _probe_backend(window_s: int = PROBE_WINDOW_S) -> tuple[str | None, str]:
    """Initialize the backend in a SUBPROCESS first: a wedged axon
    tunnel hangs ``jax.devices()`` for ~25 min before failing, which
    would look like a silent bench hang.  Returns (platform, error):
    platform is None if the backend is unusable, with the actual
    failure mode in ``error``.

    Retries at a SHORT cadence inside an env-capped window
    (``THEANOMPI_TPU_BENCH_PROBE_S``, default 30 min): round 2's single
    300 s attempt zeroed the round's official record on a transient
    wedge.  Each attempt is capped at ``PROBE_ATTEMPT_S`` (healthy
    tunnels answer in ~15-40 s) because a client that STARTS during a
    wedge fails UNAVAILABLE ~25 min later even if the tunnel recovers
    meanwhile — a single full-window blocked attempt would sleep
    through a serving window that opens mid-probe.  Round 2's
    supervisor retried every ~2 min for hours and still caught the one
    window that opened, so short-cadence kills neither prevent lease
    recovery nor miss windows."""
    # this image's sitecustomize pre-registers the axon plugin and
    # ignores the env var alone — apply it via jax.config like the
    # test conftest does, so JAX_PLATFORMS=cpu runs bench on CPU
    code = ("import os, jax\n"
            "p = os.environ.get('JAX_PLATFORMS')\n"
            "if p: jax.config.update('jax_platforms', p)\n"
            "print(jax.devices()[0].platform)")
    deadline = time.monotonic() + window_s
    attempts = 0
    last_err = "no probe attempt ran"
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 1:
            return None, (f"{last_err} — gave up after {attempts} "
                          f"attempt(s) in a {window_s}s window")
        attempts += 1
        _STATUS["probe_attempts"] = attempts
        _heartbeat(f"probe attempt {attempts} starting "
                   f"({remaining:.0f}s left in window)")
        _timeline("probe_attempt_start", attempt=attempts,
                  window_left_s=round(remaining, 1))
        t_attempt = time.monotonic()
        rc, stdout, stderr, timed_out = _run_probe_sub(
            [sys.executable, "-c", code],
            timeout=min(PROBE_ATTEMPT_S, remaining))
        if timed_out:
            # blocked in device init = wedged RIGHT NOW; a fresh client
            # after the wedge clears is the only thing that ever
            # succeeds, so kill, wait, re-probe until the window ends
            last_err = (f"device init hung past {PROBE_ATTEMPT_S}s "
                        "(wedged tunnel?)")
            _STATUS["last_error"] = last_err
            _timeline("probe_attempt_hang", attempt=attempts,
                      waited_s=round(time.monotonic() - t_attempt, 1))
            time.sleep(min(_PROBE_BACKOFF.delay(attempts - 1),
                           max(0.0, deadline - time.monotonic())))
            continue
        out = stdout.strip().splitlines()
        if rc == 0 and out:
            _timeline("backend_up", attempt=attempts,
                      platform=out[-1],
                      waited_s=round(time.monotonic() - t_attempt, 1))
            return out[-1], ""
        tail = "; ".join(stderr.strip().splitlines()[-3:])
        err = f"backend init failed (rc={rc}): {tail}"
        # bail ONLY on signatures that are deterministic by
        # construction (the misconfigs actually hit in round 2: a
        # platform name jax doesn't know, or PYTHONPATH clobbering the
        # plugin registration).  Anything else — including fast
        # UNAVAILABLE / connection-refused bursts while the tunnel
        # relay restarts — keeps retrying for the full window; timing
        # heuristics misclassify those transients and re-zero the
        # round's record, the exact failure this retry loop exists to
        # prevent.
        last_err = err
        _STATUS["last_error"] = last_err
        _timeline("probe_attempt_failed", attempt=attempts, rc=rc,
                  error=err[:200],
                  waited_s=round(time.monotonic() - t_attempt, 1))
        deterministic = ("not in the list of known backends",
                         "Unknown backend",
                         "ModuleNotFoundError", "ImportError")
        if any(s in err for s in deterministic):
            return None, f"{err} — not retrying (misconfig, not a wedge)"
        _heartbeat(f"probe attempt {attempts} failed: {err[:120]}")
        # back off (exponential + jitter), but never sleep away the
        # final attempt's window — the post-UNAVAILABLE recovery
        # attempt is the whole point
        remaining = deadline - time.monotonic()
        time.sleep(min(_PROBE_BACKOFF.delay(attempts - 1),
                       max(0.0, remaining - 60.0)))


import jax
import numpy as np


def fenced_loss(metrics) -> float:
    """Value readback — the only reliable fence on the axon plugin.
    Multi-step metrics come back stacked (k,); fence on the last."""
    return float(np.asarray(metrics["loss"]).ravel()[-1])


def main() -> int:
    # telemetry session (no-op unless $THEANOMPI_TPU_MONITOR is set):
    # probe phases become spans and the heartbeat file names the live
    # phase, so a hung bench self-diagnoses from outside instead of
    # wedging silently (the r04 blind spot)
    with monitor.session():
        return _main()


def _main() -> int:
    _install_kill_handler()
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        platform, err = "cpu", ""  # no tunnel involved; probe is moot
    else:
        _set_phase("probe")
        with monitor.span("bench/probe"):
            platform, err = _probe_backend()
    if platform is None:
        print(_failure_json(f"no measurement taken — {err}"), flush=True)
        return 1
    # persistent compilation cache: a repeat tunnel window skips the
    # measured 39.3 s ResNet-50 compile.  Opt-out by exporting an empty
    # THEANOMPI_TPU_COMPILATION_CACHE; default under artifacts/ so the
    # queue's windows share it.  Imported AFTER the probe: helper_funcs
    # pulls in jax.numpy, and a broken backend must die inside the
    # probe's failure-JSON envelope, not as a bare import traceback
    # with an empty stdout (the r04 blind spot all over again)
    from theanompi_tpu.utils.helper_funcs import (
        COMPILATION_CACHE_ENV,
        enable_compilation_cache,
    )

    if COMPILATION_CACHE_ENV not in os.environ:
        os.environ[COMPILATION_CACHE_ENV] = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "artifacts", "jax_cache")
    enable_compilation_cache()
    _set_phase(f"measure ({platform})")
    _heartbeat(f"backend up: {platform}; building model")

    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.resnet50 import ResNet50
    from theanompi_tpu.data.imagenet import ImageNet_data
    from theanompi_tpu.parallel.mesh import data_mesh, shard_batch
    from theanompi_tpu.utils.recorder import Recorder

    devices = jax.devices()
    n_chips = len(devices)
    mesh = data_mesh(n_chips, devices)

    batch_per_chip = BATCH_PER_CHIP
    global_batch = batch_per_chip * n_chips

    class BenchResNet50(ResNet50):
        def build_data(self):
            return ImageNet_data(crop=224,
                                 synthetic_n=global_batch * (E2E_STEPS + 2),
                                 synthetic_pool=64, synthetic_store=256,
                                 augment_on_device=True)

    k = STEPS_PER_CALL
    if _BENCH_K_ENV is None and jax.default_backend() == "cpu":
        k = 1   # scanned bodies are ~13x slower on the CPU backend
    cfg = ModelConfig(batch_size=batch_per_chip, n_epochs=1,
                      compute_dtype="bfloat16", track_top5=False,
                      steps_per_call=k, print_freq=10**9,
                      # the device-step leg replays 2 pre-staged
                      # batches round-robin; donation would delete
                      # them after the first pass
                      donate_batch=False)
    model = BenchResNet50(config=cfg, mesh=mesh, verbose=False)
    model.compile_iter_fns("avg")

    # ---- leg 1: device step over pre-staged uint8 batches ----
    host_it = model.data.train_batches(0, global_batch)
    if k > 1:
        from theanompi_tpu.models.base import _stack_host_batches

        stacked_it = _stack_host_batches(host_it, k)
        staged = [shard_batch(next(stacked_it), mesh,
                              spec=model.stacked_batch_spec())
                  for _ in range(2)]
        step_fn = model.train_step_multi
    else:
        staged = [shard_batch(next(host_it), mesh) for _ in range(4)]
        step_fn = model.train_step

    rng = jax.random.key(0)
    state = model.state
    _set_phase("compile+warmup")
    _heartbeat("compiling the training step (first compile ~20-40s)")
    with monitor.span("bench/compile_warmup"):
        for i in range(3):  # warmup: compile + steady state
            state, metrics = step_fn(state, staged[i % len(staged)], rng)
        fenced_loss(metrics)

    _set_phase("device-step leg")
    _heartbeat("warm; timing the device-step leg")
    n_steps = max(1, N_STEPS // k)  # dispatches; each covers k iters
    with monitor.span("bench/device_step"):
        t0 = time.perf_counter()
        for i in range(n_steps):
            state, metrics = step_fn(state, staged[i % len(staged)], rng)
        loss = fenced_loss(metrics)  # fences the whole chain
        dt = time.perf_counter() - t0
    assert np.isfinite(loss), f"non-finite loss {loss}"
    model.state = state  # keep the warm state for the e2e leg

    step_total = n_steps * k * global_batch / dt
    step_per_chip = step_total / n_chips
    del staged, host_it  # free leg-1 device buffers before the e2e leg

    # ---- H2D ceiling: what the host→device link allows ----
    # On the axon tunnel this is ~0.03 GB/s (vs tens of GB/s on a real
    # TPU VM), which caps the e2e leg far below the device step; the
    # explicit ceiling keeps the e2e fraction honest instead of
    # looking like a pipeline bug.
    _set_phase("h2d probe")
    probe = next(model.data.train_batches(0, global_batch))
    probe_bytes = sum(np.asarray(a).nbytes for a in jax.tree.leaves(probe))

    def fence_tree(tree):
        # per-leaf value readback — the only fence the axon tunnel
        # honors (block_until_ready returns early there); EVERY leaf,
        # because the labels transfer may outlive the images'
        for leaf in jax.tree.leaves(tree):
            np.asarray(leaf.ravel()[-1:])

    warm = shard_batch(probe, mesh)
    fence_tree(warm)  # compile the slice kernels outside the timer
    del warm
    t0 = time.perf_counter()
    put = shard_batch(probe, mesh)
    fence_tree(put)
    h2d_s = time.perf_counter() - t0
    # self-calibrate: the fence itself costs ~1 RTT per leaf on the
    # tunnel; re-fencing the already-resident tree measures that cost
    # so it can be subtracted from the transfer timing
    t0 = time.perf_counter()
    fence_tree(put)
    fence_cost = time.perf_counter() - t0
    # fence-RTT jitter can exceed a small transfer outright; an
    # implausible (<=0) correction keeps the uncorrected upper bound
    # rather than reporting clamp-garbage bandwidth
    h2d_s = h2d_s - fence_cost if h2d_s > fence_cost else h2d_s
    h2d_gbps = probe_bytes / h2d_s / 1e9
    h2d_ceiling_total = global_batch / h2d_s  # img/s if H2D-serial
    del put, probe

    # ---- leg 2: end-to-end through the real pipeline ----
    # train_iter covers k iterations per dispatch when steps_per_call
    # is on, so drive by consumed count like rules/bsp.py does
    _set_phase("e2e leg")
    _heartbeat(f"device step {step_per_chip:.0f} img/s/chip; e2e leg")
    recorder = Recorder(rank=0, size=n_chips, print_freq=0)
    n_iters = min(model.begin_epoch(0), E2E_STEPS)
    n_iters -= n_iters % k
    with monitor.span("bench/e2e"):
        t0 = time.perf_counter()
        it = 0
        while it < n_iters:
            it += model.train_iter(it, recorder)
        model._flush_metrics(recorder)  # device_fence on the last metrics
        e2e_dt = time.perf_counter() - t0
    model.cleanup()
    assert np.isfinite(recorder.train_losses).all()

    e2e_total = it * global_batch / e2e_dt
    e2e_per_chip = e2e_total / n_chips
    # recorder cross-check: its calc+wait seconds should explain the
    # fenced wall-clock within a few percent (VERDICT r1 #6)
    rec_accounted = sum(recorder.epoch_time[k] for k in recorder.SECTIONS)

    # Disarm the kill handler for the success print: a TERM landing
    # between the print and the phase='done' flip would append a
    # failure JSON line after (or interleaved into) the success line,
    # and a last-line parser would record 0.0 despite a completed
    # measurement (round-4 advisor finding).  Three belts, closing the
    # race from every end:
    #   1. pthread_sigmask blocks delivery to THIS (main) thread for
    #      the print window — the advisor's requested guard;
    #   2. SIG_IGN drops process-directed signals landing on any
    #      OTHER (JAX/prefetcher) thread — masking only the main
    #      thread does not cover those, since CPython runs the Python
    #      handler in the main thread regardless of which thread the
    #      OS delivered to (round-5 review);
    #   3. phase='done' flips BEFORE the print, so a handler that
    #      somehow still fires exits 0 without appending a failure
    #      line mid-stream.
    # The measurement is done; the only thing a late TERM could still
    # do is skip teardown — and the driver's SIGKILL escalation covers
    # a teardown wedge either way.
    import signal as _signal
    _signal.pthread_sigmask(_signal.SIG_BLOCK,
                            {_signal.SIGTERM, _signal.SIGINT})
    _signal.signal(_signal.SIGTERM, _signal.SIG_IGN)
    _signal.signal(_signal.SIGINT, _signal.SIG_IGN)
    _STATUS["phase"] = "done"
    print(json.dumps({
        "metric": "resnet50_imagenet_bsp_images_per_sec_per_chip",
        "value": round(step_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(step_per_chip / BASELINE_PER_CHIP, 4),
        "detail": {
            "n_chips": n_chips,
            "global_batch": global_batch,
            "steps_per_call": k,
            "images_per_sec_total": round(step_total, 2),
            "step_ms": round(dt / (n_steps * k) * 1e3, 2),
            "dispatch_ms": round(dt / n_steps * 1e3, 2),
            "e2e_images_per_sec_per_chip": round(e2e_per_chip, 2),
            "e2e_fraction_of_device_step": round(e2e_per_chip
                                                 / step_per_chip, 4),
            "h2d_gbps": round(h2d_gbps, 4),
            "h2d_ceiling_images_per_sec_per_chip": round(
                h2d_ceiling_total / n_chips, 2),
            "e2e_fraction_of_h2d_ceiling": round(
                e2e_total / h2d_ceiling_total, 4),
            "e2e_bound": ("h2d" if h2d_ceiling_total < step_total
                          else "compute"),
            "e2e_steps": it,
            "recorder_accounted_s": round(rec_accounted, 3),
            "recorder_wall_s": round(e2e_dt, 3),
            "augment": "device",
            "backend": jax.default_backend(),
        },
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
