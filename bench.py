"""Benchmark: ResNet-50 ImageNet BSP training throughput (the driver's
primary metric — BASELINE.json: images/sec/chip, north-star ≥2500
img/s on a v5e-16 ⇒ 156.25 img/s/chip).

Runs the flagship BSP training step (fwd + bwd + psum exchange + SGD
update, bf16 compute) on all available devices with synthetic
ImageNet-shaped data pre-staged on device (measures the device step,
which is what images/sec/chip compares; the input pipeline is
benchmarked by its own tests).  Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}``.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_PER_CHIP = 2500.0 / 16.0  # north-star v5e-16 target, per chip


def main() -> None:
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.resnet50 import ResNet50
    from theanompi_tpu.data.imagenet import ImageNet_data
    from theanompi_tpu.parallel.mesh import data_mesh, shard_batch

    devices = jax.devices()
    n_chips = len(devices)
    mesh = data_mesh(n_chips, devices)

    batch_per_chip = 128
    global_batch = batch_per_chip * n_chips

    class BenchResNet50(ResNet50):
        def build_data(self):
            return ImageNet_data(crop=224, synthetic_n=global_batch * 64,
                                 synthetic_pool=64, synthetic_store=256)

    cfg = ModelConfig(batch_size=batch_per_chip, n_epochs=1,
                      compute_dtype="bfloat16", track_top5=False,
                      print_freq=10**9)
    model = BenchResNet50(config=cfg, mesh=mesh, verbose=False)
    model.compile_iter_fns("avg")

    # Pre-stage a few device batches and cycle them (device-step
    # throughput; keeps host augment out of the timed region).
    host_it = model.data.train_batches(0, global_batch)
    staged = [shard_batch(next(host_it), mesh) for _ in range(4)]

    rng = jax.random.key(0)
    state = model.state

    # warmup (compile + steady state); sync via value readback — the
    # experimental axon plugin's block_until_ready returns early, so a
    # host transfer is the only reliable fence.
    for i in range(3):
        state, metrics = model.train_step(state, staged[i % len(staged)], rng)
    float(metrics["loss"])

    n_steps = 30
    t0 = time.perf_counter()
    for i in range(n_steps):
        state, metrics = model.train_step(state, staged[i % len(staged)], rng)
    loss = float(metrics["loss"])  # fences the whole chain
    dt = time.perf_counter() - t0
    assert np.isfinite(loss), f"non-finite loss {loss}"

    images_per_sec = n_steps * global_batch / dt
    per_chip = images_per_sec / n_chips
    print(json.dumps({
        "metric": "resnet50_imagenet_bsp_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_PER_CHIP, 4),
        "detail": {
            "n_chips": n_chips,
            "global_batch": global_batch,
            "images_per_sec_total": round(images_per_sec, 2),
            "step_ms": round(dt / n_steps * 1e3, 2),
            "backend": jax.default_backend(),
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
